"""Continuous-batching decode engine (slot-based, TPU-first).

The reference framework is training-only; its serving story ends at
graph export (``autodist/checkpoint/saved_model_builder.py:24-64``).
This engine is beyond-parity scope: the standard production decode
loop — a fixed pool of ``slots`` sequences decoding in lockstep, where
finished sequences are harvested and new requests admitted *without
stopping the batch* — built on the same single-definition block math as
training (``models/transformer.py``) via ``models/generate._token_step``.

TPU-first design points:

* **One compiled program, static shapes.**  The engine state is a fixed
  ``[slots, window]`` token buffer and a time-major KV cache
  ``[L, window, slots, H, Dh]``.  A chunk of ``chunk`` decode ticks is
  one jitted ``lax.scan``; admission/harvest happen between chunks on
  the host.  No recompiles at request boundaries.
* **Uniform cache write index over a RING.**  Every tick writes every
  slot's K/V at the same *ring* index ``tick % window``, so the cache
  update stays the one contiguous ``dynamic_update_slice`` that makes
  the decode tick fast (the ~10× batch-major-vs-time-major lesson
  recorded in BASELINE.md) while the engine tick itself grows without
  bound.  Per-request sequence positions are recovered by offset: a
  slot admitted at tick ``start`` (an *absolute* tick, unbounded) uses
  ``pos_embed[tick - start]`` and attends the ring positions
  ``(pos - start) % window <= tick - start``.  Because ``submit``
  bounds every request's span by ``window``, a slot's live region
  never wraps onto itself, and the attended window of an active slot
  is always positions the *current* occupant wrote — so slot reuse
  needs no cache zeroing, and a free slot can admit at ANY tick: one
  long request can never stall the pool (no drain, no window reset —
  the round-4 head-of-line blocker).
* **Token-exact.**  Greedy engine output equals ``make_generator``'s
  for each request individually: the extra masked positions contribute
  exactly-zero attention weight (``exp(min - max) == 0``), so the
  numerics are identical, not approximately so (pinned in
  ``tests/test_serving_engine.py``).
* **Parallel prefill.**  Every admission (when ``prefill=True``, the
  default) charges its prompt into the cache with ONE [P]-parallel
  causal forward (``models/generate._prefill_forward`` — MXU-shaped
  matmuls) instead of P sequential decode ticks: the prompt's K/V land
  at ring positions ``(t0-P..t0-1) % window`` — behind the admission
  tick, wrapping when ``t0 < P`` — and the slot joins the global tick
  already generating.  Prefill logits equal the tick-by-tick logits up
  to float reduction order (the documented allclose-level equivalence
  of parallel vs cached attention), so greedy parity with ``generate``
  holds on non-tied argmaxes — the deterministic case the tests pin.

Admission is FIFO at chunk boundaries and always succeeds to a free
slot (a request's whole ``prompt + max_new`` span must fit inside
``window``, which is exactly the ring-safety invariant).
"""
from __future__ import annotations

import functools
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from autodist_tpu.models.base import ModelSpec
from autodist_tpu.models.generate import (_prefill_forward, _token_step,
                                          _vocab_size, check_sampling_args,
                                          embed_lookup, require_lm_spec,
                                          sample_next_token,
                                          unpack_lm_params)
from autodist_tpu.models.quantize import head_logits


TEMPERATURE_FLOOR = 1e-6
"""Smallest accepted nonzero per-request temperature.  Below it the
scaled logits overflow f32 (|logit|/temp > f32 max) and the softmax
NaNs, so ``submit`` rejects the range instead of silently clamping —
``temperature=0`` is the supported way to ask for greedy."""


class AdmissionError(RuntimeError):
    """Typed backpressure: a submit was rejected because the request
    queue (or an SLO class's share of it) is full.  Carries a
    ``retry_after_s`` hint derived from the observed completion rate —
    the HTTP front surfaces this as 429 with a ``Retry-After`` header
    (``serving/server.py``), and the router treats it as
    route-elsewhere, not request-failed."""

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)


class DeadlineError(RuntimeError):
    """Typed deadline shed: a submit was rejected because the measured
    queue-wait/per-token percentiles say the request cannot finish
    inside its ``deadline_s``.  Distinct from :class:`AdmissionError`
    (queue full): the queue may be shallow — the request itself is
    infeasible under current service rates.  The HTTP front surfaces
    this as 503 + ``Retry-After`` with ``"shed": true``, which the
    router treats as route-elsewhere WITHOUT marking the replica down
    (shedding is a load signal, not a health signal)."""

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)


def check_speculative_args(gamma, temperature, *, span=None,
                           window=None) -> None:
    """Submit-time validation of speculative-decoding knobs, mirroring
    the temperature-floor rule: a knob combination that would fail (or
    silently diverge) mid-run is rejected as a typed ``ValueError`` at
    submit instead.  ``gamma`` must be >= 1; greedy acceptance is only
    target-exact at ``temperature == 0``; and the verify window needs
    ``gamma`` slack positions past ``span = prompt + max_new_tokens``
    (draft proposals may overshoot before being trimmed)."""
    if int(gamma) < 1:
        raise ValueError(f"speculative gamma must be >= 1, got {gamma}")
    if float(temperature) != 0.0:
        raise ValueError(
            f"speculative decoding is greedy-only (temperature 0): "
            f"greedy acceptance guarantees target-exact output, "
            f"sampled acceptance does not; got temperature="
            f"{temperature}")
    if span is not None and window is not None \
            and span + int(gamma) > int(window):
        raise ValueError(
            f"prompt + max_new_tokens + gamma = {span + int(gamma)} "
            f"exceeds the engine window {window}; shrink gamma, "
            f"raise window=, or split the request")


def _sample_per_slot(logits, key, temp, top_k, top_p):
    """Per-slot temperature over one logits batch [B, V]: rows with
    ``temp[b] == 0`` take the argmax, others sample from
    ``logits / temp[b]`` through the engine-wide static top-k/top-p
    filters (``sample_next_token`` at temperature 1.0 on the pre-scaled
    logits — the single definition of the filters).  ``submit`` rejects
    temperatures in (0, TEMPERATURE_FLOOR), so the floor below only
    guards the greedy rows' dummy divide, never alters a request."""
    greedy = jnp.argmax(logits, axis=-1)
    scaled = logits.astype(jnp.float32) \
        / jnp.maximum(temp, TEMPERATURE_FLOOR)[:, None]
    sampled = sample_next_token(scaled, key, 1.0, top_k, top_p)
    return jnp.where(temp > 0.0, sampled, greedy)


# The two compiled programs live at module scope so the jit cache is
# shared across DecodeEngine instances: a server that rebuilds its
# engine (model reload, knob change) re-traces nothing that an earlier
# instance already compiled.  All configuration enters either through
# array shapes (cache layout carries L/window/slots/heads/head_dim) or
# through the static ``knobs`` tuple (top_k, top_p, prefix_len);
# temperature and eos ride as TRACED per-slot vectors (per-request
# values, no recompiles), and dispatches that don't touch the prefix
# pass prefix_len=0 + dummy kp/vp so the plain programs' compile-cache
# key is independent of any registered prefix.

@functools.partial(jax.jit, static_argnums=(0, 1),
                   donate_argnums=(3, 4, 5))
def _chunk_program(n, knobs, params, tokens, kc, vc, start, p_end, end,
                   done, active, temp, eos, use_prefix, kp, vp,
                   tick0, key):
    """``n`` decode ticks of all slots in lockstep (see DecodeEngine).

    ``temp`` [B] f32 and ``eos`` [B] i32 are TRACED per-slot sampling
    knobs (temperature 0 = greedy; eos -1 = none): per-REQUEST values
    ride through without recompiles.  ``knobs`` = (top_k, top_p, plen)
    stay static — filter branches and the registered prefix length.

    ``kp``/``vp`` [L, Ppb, H, Dh] hold the SHARED cached prefix (one
    copy, every opted-in slot attends it — ``use_prefix`` [B]); with no
    prefix registered they are [L, 1, H, Dh] zeros, plen=0, and the
    prefix math vanishes at trace time."""
    top_k, top_p, plen = knobs
    num_layers, window = kc.shape[0], kc.shape[1]
    embed, pos_embed, layer_params, ln_final = unpack_lm_params(
        params, num_layers)
    pos_idx = jnp.arange(window)[None, :]                 # [1, W]
    if plen:
        pmask = use_prefix[:, None] \
            & (jnp.arange(kp.shape[1]) < plen)[None, :]   # [B, Ppb]
        pos_off = jnp.where(use_prefix, plen, 0)          # [B]
        prefix_kv = (kp, vp)
    else:
        pmask, pos_off, prefix_kv = None, 0, None

    def one_tick(carry, i):
        tokens, kc, vc, done, key = carry
        t = tick0 + i                                     # absolute tick
        t_ring = jnp.mod(t, window)                       # ring write pos
        tok = lax.dynamic_index_in_dim(tokens, t_ring, 1, keepdims=False)
        # sequence position: prefix length offsets opted-in slots
        rel = jnp.clip(t - start, 0, window - 1) + pos_off  # [B]
        x = embed_lookup(embed, tok, pos_embed.dtype) + pos_embed[rel]
        # Ring mask: slot b attends ring positions its CURRENT occupant
        # wrote — sequence offsets 0..t-start[b], laid out mod window.
        mask = jnp.mod(pos_idx - start[:, None], window) \
            <= (t - start)[:, None]
        logits, kc, vc = _token_step(
            layer_params, ln_final, embed, x, kc, vc, t_ring, window,
            attn_mask=mask, prefix_kv=prefix_kv, prefix_mask=pmask)
        key, sub = jax.random.split(key)
        raw = _sample_per_slot(logits, sub, temp, top_k,
                               top_p).astype(tokens.dtype)
        busy = jnp.sum((active & ~done).astype(jnp.int32))
        # Teacher-force while inside the prompt; only live slots write;
        # a finished slot's buffer is left as-is (harvest pads eos on
        # the host).
        w_ring = jnp.mod(t + 1, window)
        cur = lax.dynamic_index_in_dim(tokens, w_ring, 1, keepdims=False)
        in_gen = t + 1 >= p_end                           # [B]
        live = active & ~done
        nxt = jnp.where(in_gen & live, raw, cur)
        tokens = lax.dynamic_update_index_in_dim(tokens, nxt, w_ring, 1)
        # per-slot eos (-1 = none, never matches a generated id >= 0)
        done = done | (in_gen & live & (raw == eos))
        # The final token of slot b lands at buffer index end[b]-1,
        # written by tick end[b]-2.
        done = done | (t + 2 >= end)
        return (tokens, kc, vc, done, key), busy

    (tokens, kc, vc, done, key), busy = lax.scan(
        one_tick, (tokens, kc, vc, done, key), jnp.arange(n))
    return tokens, kc, vc, done, jnp.sum(busy)


@functools.partial(jax.jit, static_argnums=(0, 1, 2),
                   donate_argnums=(4, 5, 6))
def _prefill_program(knobs, with_prefix, contiguous, params, tokens, kc,
                     vc, prompts_kpb, slot_ids, row_map, t0, p_lens, temp,
                     kp, vp, key):
    """Parallel prefill, batched over the boundary's admissions: ONE
    [K, Pb]-parallel causal forward (MXU-shaped) charges K slots' K/V
    instead of Σ P sequential ticks or K separate dispatches, and
    samples each slot's first generated token.  Each prompt lands at
    cache positions ``t0-P..t0-1`` — *behind* the shared admission tick
    — so the slots join the global tick already in generation phase;
    the token-buffer rows get the prompts and sampled tokens in the
    same program (the buffer is device-resident).  ``temp`` [slots] is
    the traced per-SLOT temperature vector (indexed by ``slot_ids`` for
    each admitted row's first sampled token).  ``prompts_kpb``
    [K, Pb]: Pb is the rows' shared pow-2 prompt bucket and K a pow-2
    sub-batch size, both chosen by the scheduler (``_flush_prefills``)
    so the set of compiled (K, Pb) programs stays small.  Writes land
    at RING positions ``(t0-P..t0-1) % window`` (``t0`` is absolute and
    ``t0 - P`` may be negative — the mod wraps both); pad positions'
    K/V and pad token writes land at ring positions >= t0 and are
    overwritten by each tick's own write before any read sees them
    (``Pb <= window``, enforced by ``_prompt_bucket``, keeps the pad
    tail off the prompt itself).  ``p_lens`` may differ per row
    (prompts right-padded to Pb).

    ``row_map`` [S] maps each target SLOT entry to its unique prompt
    row — identical prompts admitted together (system-prompt fan-out,
    n samples per prompt) are computed ONCE and their K/V scattered to
    every slot; under temperature sampling each slot still draws its
    own independent first token from the shared logits row.

    ``with_prefix`` (static): this dispatch's rows all attend the
    shared cached prefix ``kp``/``vp`` (the scheduler groups admissions
    by prefix use) — their forward runs through ``_prefill_forward``'s
    prefix seam with positions offset by the static ``plen`` in
    ``knobs``.

    ``contiguous`` (static): this dispatch's rows' ring ranges do NOT
    wrap the window (``(t0 - p_j) % window + Pb <= window``, decided on
    the host — ``_flush_prefills`` groups admissions by wrapness), so
    each row's K/V charge is ONE ``dynamic_update_slice`` spanning all
    layers — the contiguous cache write the module docstring's
    batch-major lesson is about — instead of a per-column scatter.
    Wrapped dispatches (only possible once the ring has cycled, i.e.
    ``t0 % window < p_j``) take the mod-window scatter path."""
    top_k, top_p, plen = knobs
    num_layers, _, _, heads, head_dim = kc.shape
    embed, pos_embed, layer_params, ln_final = unpack_lm_params(
        params, num_layers)
    xs, ks, vs = _prefill_forward(
        layer_params, ln_final, embed, pos_embed, prompts_kpb, heads,
        head_dim,
        prefix_kv=(kp, vp) if with_prefix else None,
        plen=plen if with_prefix else 0)
    s_count = slot_ids.shape[0]
    pb = prompts_kpb.shape[1]
    window = kc.shape[1]
    for j in range(s_count):                  # S is static (shape)
        i = row_map[j]
        row_k = lax.dynamic_index_in_dim(ks, i, 1)   # [L, 1, Pb, H, Dh]
        row_v = lax.dynamic_index_in_dim(vs, i, 1)
        p_j = p_lens[i]
        sb = slot_ids[j]
        prow = lax.dynamic_index_in_dim(prompts_kpb, i, 0)  # [1, Pb]
        if contiguous:
            # Fast path: the whole Pb range is one contiguous window
            # segment starting at (t0 - p_j) % window.
            s0 = jnp.mod(t0 - p_j, window).astype(jnp.int32)
            blk_k = jnp.swapaxes(row_k, 1, 2)     # [L, Pb, 1, H, Dh]
            blk_v = jnp.swapaxes(row_v, 1, 2)
            kc = lax.dynamic_update_slice(
                kc, blk_k.astype(kc.dtype), (0, s0, sb, 0, 0))
            vc = lax.dynamic_update_slice(
                vc, blk_v.astype(vc.dtype), (0, s0, sb, 0, 0))
            tokens = lax.dynamic_update_slice(
                tokens, prow.astype(tokens.dtype), (sb, s0))
            continue
        # Wrapped range: per-column scatter over the mod-window indices
        # (≤ 2 segments, but their lengths are traced — the scatter is
        # the shape-stable form).
        idx = jnp.mod(t0 - p_j + jnp.arange(pb), window)  # [Pb]
        kc = kc.at[:, idx, sb].set(row_k[:, 0].astype(kc.dtype))
        vc = vc.at[:, idx, sb].set(row_v[:, 0].astype(vc.dtype))
        tokens = tokens.at[sb, idx].set(prow[0].astype(tokens.dtype))
    last = jnp.take_along_axis(
        xs, (p_lens - 1)[:, None, None].astype(jnp.int32), axis=1
    )[:, 0]                                               # [K, D]
    logits = head_logits(embed, last)                     # [K, V]
    logits_s = jnp.take(logits, row_map, axis=0)          # [S, V]
    temp_s = jnp.take(temp, slot_ids)                     # [S]
    toks = _sample_per_slot(logits_s, key, temp_s, top_k, top_p)
    t0r = jnp.mod(t0, window)
    tokens = tokens.at[slot_ids, t0r].set(toks.astype(tokens.dtype))
    # Report the values that LANDED in the buffer, not the raw draws:
    # S is padded to a pow-2 bucket with duplicated entries, and when
    # duplicate slot indices scatter different samples the winner is
    # unspecified — reading back keeps the host's eos bookkeeping
    # consistent with what the next tick will actually consume.
    landed = tokens[slot_ids, t0r]
    return tokens, kc, vc, landed


@functools.partial(jax.jit, static_argnums=(2, 3, 4))
def _prefix_kv_program(params, tokens_1p, num_layers, heads, head_dim):
    """One-time K/V computation for a registered shared prefix: one
    causal forward over the (bucketed) prefix tokens, returning
    ``(kp, vp)`` each [L, Ppb, H, Dh].  Pad positions' K/V are garbage
    but masked by the static plen everywhere they could be read."""
    embed, pos_embed, layer_params, ln_final = unpack_lm_params(
        params, num_layers)
    _, ks, vs = _prefill_forward(layer_params, ln_final, embed,
                                 pos_embed, tokens_1p, heads, head_dim)
    return ks[:, 0], vs[:, 0]


@functools.lru_cache(maxsize=None)
def _sharded_zeros(shape, dtype, sharding):
    """Cached jitted zero-init producing a buffer DIRECTLY in
    ``sharding`` (never materialized on one device); cached so engine
    rebuilds re-trace nothing, like the other module-scope programs.
    Each call of the returned program yields a fresh donatable buffer."""
    return jax.jit(lambda: jnp.zeros(shape, dtype),
                   out_shardings=sharding)


@functools.partial(jax.jit, donate_argnums=(0,))
def _write_prompt_program(tokens, prompt_pb, slot_b, t0):
    """Sequential-admission prompt write into the device-resident token
    buffer: row ``slot_b`` RING positions ``(t0..t0+Pb-1) % window``
    (pow-2 bucket; the pad tail lands on future tick-write positions of
    the same slot and is overwritten before any read sees it)."""
    idx = jnp.mod(jnp.int32(t0) + jnp.arange(prompt_pb.shape[0]),
                  tokens.shape[1])
    return tokens.at[jnp.int32(slot_b), idx].set(
        prompt_pb.astype(tokens.dtype))


@dataclass
class Request:
    """One decode request: ``prompt`` is a 1-D int array; the engine
    appends up to ``max_new_tokens`` (fewer if ``eos_id`` fires).
    ``temperature``/``eos_id`` override the engine defaults per request
    (traced per-slot values — no recompiles).  ``prefix`` PINS the
    prefix KV generation this request was submitted under —
    ``(kp, vp, plen)`` — so ``set_prefix``/``clear_prefix`` mid-flight
    can never swap cached context out from under an admitted request
    (the pin holds the old arrays alive until the last reader
    finishes)."""
    prompt: np.ndarray
    max_new_tokens: int
    request_id: int = -1
    temperature: float = 0.0
    eos_id: int = -1
    use_prefix: bool = False
    prefix: Optional[tuple] = None    # (kp, vp, plen) pinned at submit


@dataclass
class EngineStats:
    """Aggregate engine counters (monotonic over the engine lifetime)."""
    ticks: int = 0                # engine ticks executed
    busy_slot_ticks: int = 0      # sum over ticks of unfinished slots
    generated_tokens: int = 0     # tokens actually produced (post-prompt)
    prompt_tokens: int = 0        # prompt tokens consumed (all admissions)
    prefilled_tokens: int = 0     # of those, charged by parallel prefill
    prefill_admissions: int = 0   # admissions that used parallel prefill
    prefill_dispatches: int = 0   # batched prefill programs dispatched
    prefill_dedup_hits: int = 0   # slots served by a shared prompt row
    prefix_admissions: int = 0    # requests decoding against the prefix
    completed: int = 0            # requests harvested
    chunks: int = 0               # compiled-program dispatches

    @property
    def slot_utilization(self) -> float:
        """Fraction of slot-ticks spent on an unfinished request."""
        total = self.ticks * self._slots if self._slots else 0
        return self.busy_slot_ticks / total if total else 0.0

    _slots: int = field(default=0, repr=False)


class DecodeEngine:
    """Continuous-batching decode over a ``transformer_lm`` ModelSpec.

    Usage::

        eng = DecodeEngine(spec, params, slots=8, window=512)
        rid = eng.submit(prompt_1d, max_new_tokens=64)
        results = eng.run()          # {rid: np.ndarray tokens}

    ``params`` may be full precision or a weight-only int8 tree from
    :func:`autodist_tpu.models.quantize.quantize_lm_params` (the tick
    math routes through the same Pallas int8 kernel as ``generate``).

    Sampling: ``temperature`` and ``eos_id`` here are DEFAULTS that each
    ``submit(..., temperature=, eos_id=)`` may override per request —
    they ride the compiled programs as traced per-slot vectors, so mixed
    greedy/sampled batches share one program with no recompiles.
    ``top_k``/``top_p`` stay engine-wide trace-time constants (filter
    branches).  ``temperature=0`` is greedy.

    ``mesh``/``slot_axis``: multi-chip serving — shard the slot pool
    over a mesh axis (the axis size must divide ``slots``).  Per-slot
    decode has no cross-slot math, so each device decodes its own slots
    with no collectives; composes with model-axis-sharded (TP) params.
    """

    def __init__(self, spec: ModelSpec, params, *, slots: int = 8,
                 window: int = 512, chunk: int = 16,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 0.0, eos_id: Optional[int] = None,
                 rng: Optional[jax.Array] = None, prefill: bool = True,
                 mesh=None, slot_axis: str = "data",
                 max_queue: int = 1024):
        require_lm_spec(spec, "DecodeEngine")
        cfg = spec.config
        if window > cfg["max_len"]:
            raise ValueError(
                f"window={window} exceeds the model's max_len "
                f"{cfg['max_len']} (pos_embed rows)")
        if slots < 1 or window < 2 or chunk < 1:
            raise ValueError("need slots >= 1, window >= 2, chunk >= 1")
        if mesh is not None:
            if slot_axis not in mesh.axis_names:
                raise ValueError(
                    f"slot_axis {slot_axis!r} not in mesh axes "
                    f"{mesh.axis_names}")
            n_shards = mesh.shape[slot_axis]
            if slots % n_shards:
                raise ValueError(
                    f"slots={slots} must divide over the {slot_axis!r} "
                    f"axis ({n_shards} shards)")
        vocab = _vocab_size(params)
        # Same contract as make_generator (shared validation): a silent
        # fixed key would make every engine sample the identical stream.
        check_sampling_args(vocab, temperature, top_k, top_p, eos_id, rng)

        self._spec = spec
        self._params = params
        self._cfg = cfg
        self._slots = slots
        self._window = window
        self._chunk = chunk
        self._temperature = float(temperature)
        self._top_k = int(top_k)
        self._top_p = float(top_p)
        self._eos_id = -1 if eos_id is None else int(eos_id)
        self._rng = rng if rng is not None else jax.random.PRNGKey(0)
        self._vocab = vocab
        self._prefill = bool(prefill)
        if max_queue < 1:
            raise ValueError("max_queue must be >= 1")
        self._max_queue = int(max_queue)

        # Host-side scheduler state.
        self._queue: List[Request] = []
        self._next_id = 0
        self._results: Dict[int, np.ndarray] = {}
        self._slot_req: List[Optional[Request]] = [None] * slots
        self.stats = EngineStats(_slots=slots)

        self._mesh = mesh
        self._slot_axis = slot_axis
        # Multi-PROCESS serving (slot pool sharded across machines): the
        # host scheduler runs identically in every process (same inputs,
        # same numpy bookkeeping → SPMD lockstep dispatches), but host
        # pulls of device state must go through a replicating identity
        # program — a non-addressable shard (another process's slots)
        # cannot be np.array'd directly.  Single-process engines keep the
        # direct (collective-free) pulls.
        self._replicate = None
        self._replicate2 = None
        self._pull_row = None
        if mesh is not None and jax.process_count() > 1:
            from jax.sharding import NamedSharding, PartitionSpec as P

            rep = NamedSharding(mesh, P())
            self._replicate = jax.jit(
                lambda x: x, out_shardings=rep)
            # done+busy replicate in ONE program: the per-chunk hot path
            # pays one collective launch, not two.
            self._replicate2 = jax.jit(
                lambda a, b: (a, b), out_shardings=(rep, rep))
            self._pull_row = jax.jit(
                lambda t, b: lax.dynamic_index_in_dim(
                    t, b, 0, keepdims=False),
                out_shardings=rep)
        self._alloc_state()

        # The static half of the compiled programs' signature (see the
        # module-level _chunk_program/_prefill_program); temperature and
        # eos ride as traced per-slot vectors; the third knob is the
        # registered prefix length (0 = none).
        self._knobs = (self._top_k, self._top_p, 0)
        self._rng_explicit = rng is not None
        # Shared prefix cache (set_prefix): K/V held ONCE, attended by
        # opted-in slots.  The dummies keep ONE program signature when
        # no prefix is registered (plen=0 erases the math at trace time).
        heads, hd = cfg["num_heads"], cfg["head_dim"]
        pdtype = self._params["pos_embed"].dtype
        self._kp0 = jnp.zeros((cfg["num_layers"], 1, heads, hd), pdtype)
        self._kp = self._vp = self._kp0
        self._prefix_tokens: Optional[np.ndarray] = None
        # The prefix generation currently decoded by ACTIVE slots:
        # admitted prefix requests all pin ONE (kp, vp, plen) tuple at a
        # time (a request pinning a DIFFERENT generation waits in the
        # queue until the last reader of the current one finishes), so
        # the chunk program's single prefix input stays well-defined
        # while set_prefix/clear_prefix swap freely mid-flight.
        self._active_prefix: Optional[tuple] = None
        self._active_prefix_users = 0
        self._prefix_pin: Optional[tuple] = None
        # Set when a device dispatch raises mid-flight: the state
        # buffers were DONATED to the failed program and may be invalid,
        # so the engine refuses further use instead of decoding garbage.
        self._poisoned = False

    def _alloc_state(self) -> None:
        """(Re)allocate the engine state.  The token buffer and KV
        cache are DEVICE-resident: the per-chunk host traffic is only
        the [B] `done` vector down and the tiny [B] metadata vectors up
        — harvest/partial pull single finished rows.  (Pulling the
        whole [B, W] buffer every chunk measurably dominated the loop
        when ticks are cheap.)  start/p_end/end/done/active live on the
        host (admission edits them in numpy)."""
        slots, window, cfg = self._slots, self._window, self._cfg
        # Drop any previous buffers BEFORE allocating: on a healthy
        # reset() the old cache is still live, and holding both would
        # transiently double device memory — an OOM at exactly the
        # cache sizes the sharded path exists to serve.
        self._tokens = self._kc = self._vc = None
        self._start = np.zeros(slots, np.int32)
        self._p_end = np.zeros(slots, np.int32)
        self._end = np.zeros(slots, np.int32)
        self._done = np.ones(slots, bool)
        self._active = np.zeros(slots, bool)
        # per-slot sampling knobs (set at admission from the request)
        self._temp = np.full(slots, self._temperature, np.float32)
        self._eos = np.full(slots, self._eos_id, np.int32)
        self._use_prefix = np.zeros(slots, bool)
        self._tick = 0
        heads, hd = cfg["num_heads"], cfg["head_dim"]
        dtype = self._params["pos_embed"].dtype
        cache_shape = (cfg["num_layers"], window, slots, heads, hd)
        if self._mesh is None:
            # Separate buffers: kc/vc are both donated to the chunk
            # program, and donating one array through two arguments is
            # an aliasing error.
            self._tokens = jnp.zeros((slots, window), jnp.int32)
            self._kc = jnp.zeros(cache_shape, dtype)
            self._vc = jnp.zeros(cache_shape, dtype)
        else:
            # Multi-chip serving: shard the SLOT pool over a mesh axis.
            # Per-slot decode has no cross-slot math, so GSPMD runs each
            # shard's slots on its own devices with no collectives in
            # the chunk program; donation keeps the shardings chunk to
            # chunk.  (With model-axis-sharded params, TP composes: the
            # per-tick einsums shard exactly as in training.)  Buffers
            # are created DIRECTLY sharded — materializing the full
            # cache on one device first would OOM exactly the multi-chip
            # cache sizes this mode exists for.
            from jax.sharding import NamedSharding, PartitionSpec as P

            row = NamedSharding(self._mesh, P(self._slot_axis))
            cache = NamedSharding(self._mesh,
                                  P(None, None, self._slot_axis))
            self._tokens = _sharded_zeros(
                (slots, window), jnp.int32, row)()
            # two separate calls -> two distinct donatable buffers
            self._kc = _sharded_zeros(cache_shape, dtype, cache)()
            self._vc = _sharded_zeros(cache_shape, dtype, cache)()

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------
    def reset(self) -> None:
        """Drop ALL engine state — queued requests, in-flight slots,
        unfetched results — and reallocate the device buffers.  This
        also revives a poisoned engine (the compiled programs live in
        the module-scope jit cache, so recovery from a failed dispatch
        costs an allocation, not a recompile).  Call ``results()``
        first if completed-but-unfetched outputs matter."""
        self._queue.clear()
        self._results.clear()
        self._slot_req = [None] * self._slots
        self._active_prefix = None
        self._active_prefix_users = 0
        self._alloc_state()
        self._poisoned = False

    def _check_usable(self) -> None:
        if self._poisoned:
            raise RuntimeError(
                "DecodeEngine is poisoned: a device dispatch failed "
                "after its state buffers were donated (e.g. a dropped "
                "TPU connection mid-chunk); in-flight requests are "
                "lost — rebuild the engine and resubmit")

    def set_prefix(self, tokens) -> int:
        """Register a SHARED cached prefix (system prompt): its K/V are
        computed once and held as one ``[L, Pp, H, Dh]`` copy that every
        ``submit(..., use_prefix=True)`` request attends in addition to
        its own ring window — no per-slot storage, no per-admission
        recompute.  Returns the prefix length.  Replaces any previous
        prefix for FUTURE submits; requests already submitted keep the
        generation they pinned (``Request.prefix``), so a mid-flight
        swap can never change the context an admitted request decodes
        against — new-generation requests simply wait in the queue
        until the last reader of the old one finishes."""
        self._check_usable()
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        if tokens.size < 1:
            raise ValueError("prefix must have at least one token")
        if not np.all((tokens >= 0) & (tokens < self._vocab)):
            raise ValueError("prefix tokens out of vocab range")
        if tokens.size + 2 > self._cfg["max_len"]:
            raise ValueError(
                f"prefix length {tokens.size} leaves no room under the "
                f"model's max_len {self._cfg['max_len']}")
        plen = int(tokens.size)
        ppb = 1 << (plen - 1).bit_length()      # pow-2 compile bucket
        if ppb > self._cfg["max_len"]:
            ppb = plen     # exact-size fallback (same rule as
            #                _prompt_bucket): the bucket's pos_embed
            #                rows must exist
        padded = np.zeros(ppb, np.int32)
        padded[:plen] = tokens
        cfg = self._cfg
        kp, vp = _prefix_kv_program(
            self._params, jnp.asarray(padded)[None],
            cfg["num_layers"], cfg["num_heads"], cfg["head_dim"])
        self._kp, self._vp = kp, vp
        self._prefix_tokens = tokens
        self._knobs = (self._top_k, self._top_p, plen)
        self._prefix_pin = (kp, vp, plen)
        return plen

    def clear_prefix(self) -> None:
        """Drop the registered prefix for FUTURE submits.  In-flight
        and queued requests keep their pinned generation — its K/V stay
        referenced through the pins and are freed (ordinary array
        refcounting) when the last reader finishes."""
        self._check_usable()
        self._kp = self._vp = self._kp0
        self._prefix_tokens = None
        self._knobs = (self._top_k, self._top_p, 0)
        self._prefix_pin = None

    @property
    def prefix_len(self) -> int:
        return 0 if self._prefix_tokens is None \
            else int(self._prefix_tokens.size)

    def submit(self, prompt, max_new_tokens: int, *,
               temperature: Optional[float] = None,
               eos_id: Optional[int] = None,
               use_prefix: bool = False) -> int:
        """Queue a request; returns its id.  ``prompt`` is 1-D ints.
        ``temperature``/``eos_id`` override the engine defaults for THIS
        request only (per-slot traced values — no recompiles); the
        top-k/top-p filters stay engine-wide.  ``use_prefix=True``
        prepends the engine's registered shared prefix (:meth:`set_prefix`)
        as cached context — the result contains only prompt+generated.

        Raises :class:`AdmissionError` (typed backpressure, carrying a
        ``retry_after_s`` hint) when the request queue is at
        ``max_queue`` — the queue is bounded so a traffic spike shows
        up as explicit rejects, not an unbounded host-memory balloon
        with minutes-deep latency."""
        self._check_usable()
        if len(self._queue) >= self._max_queue:
            raise AdmissionError(
                f"request queue full ({self._max_queue}); retry later",
                retry_after_s=self._retry_hint())
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must have at least one token")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        span = prompt.size + int(max_new_tokens)
        if span > self._window:
            raise ValueError(
                f"prompt + max_new_tokens = {span} exceeds the engine "
                f"window {self._window}; raise window= (model max_len "
                f"{self._cfg['max_len']}) or split the request")
        if use_prefix:
            if self._prefix_tokens is None:
                raise ValueError("use_prefix=True but no prefix is "
                                 "registered (call set_prefix first)")
            if self.prefix_len + span > self._cfg["max_len"]:
                raise ValueError(
                    f"prefix ({self.prefix_len}) + prompt + "
                    f"max_new_tokens ({span}) exceeds the model's "
                    f"max_len {self._cfg['max_len']} (pos_embed rows)")
        if not np.all((prompt >= 0) & (prompt < self._vocab)):
            raise ValueError("prompt tokens out of vocab range")
        if temperature is None:
            temperature = self._temperature
        else:
            temperature = float(temperature)
            if not np.isfinite(temperature) or temperature < 0.0:
                raise ValueError(f"temperature must be a finite number "
                                 f">= 0, got {temperature}")
            if temperature > 0.0 and float(np.float32(temperature)) == 0.0:
                # would underflow to exact 0 in the f32 per-slot vector
                # and silently decode greedy while "sampled" was asked
                raise ValueError(f"temperature {temperature} underflows "
                                 f"float32; use 0 for greedy or >= 1e-6")
            if 0.0 < temperature < TEMPERATURE_FLOOR:
                # below the floor the scaled logits overflow f32 and the
                # softmax NaNs; the sampler would otherwise clamp to the
                # floor, silently diverging from the requested value
                raise ValueError(
                    f"temperature {temperature} is below the sampling "
                    f"floor {TEMPERATURE_FLOOR}; use 0 for greedy or >= "
                    f"{TEMPERATURE_FLOOR}")
            if (temperature > 0.0 and self._temperature <= 0.0
                    and not self._rng_explicit):
                raise ValueError(
                    "per-request temperature sampling on a greedy-built "
                    "engine needs an explicit rng= at engine "
                    "construction (a silent fixed key would sample the "
                    "identical stream every run)")
        if eos_id is None:
            eos_id = self._eos_id
        else:
            eos_id = int(eos_id)
            # -1 explicitly DISABLES eos for this request (the program's
            # own 'none' sentinel) — the way to ask for an unterminated
            # fixed-length generation on an eos-defaulted engine.
            if eos_id != -1 and not 0 <= eos_id < self._vocab:
                raise ValueError(f"eos_id must be -1 (none) or in [0, "
                                 f"vocab_size={self._vocab}), got {eos_id}")
        req = Request(prompt, int(max_new_tokens), self._next_id,
                      temperature=temperature, eos_id=eos_id,
                      use_prefix=bool(use_prefix),
                      prefix=(self._prefix_pin if use_prefix else None))
        self._next_id += 1
        self._queue.append(req)
        return req.request_id

    def _retry_hint(self) -> float:
        """Retry-After estimate for a rejected submit: roughly how long
        until the queue has drained one request (queue depth x the
        recent per-request wall time over the slot count), clamped to
        something a client can act on."""
        per_req = self._avg_request_s or 1.0
        est = (len(self._queue) + 1) * per_req / max(self._slots, 1)
        return float(min(60.0, max(0.1, est)))

    _avg_request_s: float = 0.0

    def run(self) -> Dict[int, np.ndarray]:
        """Decode until the queue and all slots drain; returns and
        clears ``{request_id: tokens}`` (prompt included, truncated
        after a generated ``eos_id``)."""
        self._check_usable()
        while self._schedule():
            self._run_chunk()
        self._harvest()
        out, self._results = self._results, {}
        return out

    def step(self) -> bool:
        """One schedule+chunk iteration; False when fully drained.
        (``run`` is the batch wrapper; ``step`` lets a caller interleave
        submits with decoding — the continuous-batching loop proper.)"""
        self._check_usable()
        if not self._schedule():
            self._harvest()
            return False
        self._run_chunk()
        return True

    def results(self) -> Dict[int, np.ndarray]:
        """Completed results so far (and clears them).  Usable on a
        poisoned engine: already-harvested results live on the host and
        survive a failed dispatch (only in-flight work is lost)."""
        if not self._poisoned:
            self._harvest()
        out, self._results = self._results, {}
        return out

    def cancel(self, request_id: int) -> bool:
        """Cancel a queued or in-flight request.  Returns True if it was
        found and cancelled (its slot frees at the next boundary; any
        tokens already generated are discarded), False if unknown or
        already completed."""
        for qi, req in enumerate(self._queue):
            if req.request_id == request_id:
                self._queue.pop(qi)
                return True
        for b in range(self._slots):
            req = self._slot_req[b]
            if req is not None and req.request_id == request_id:
                # Freeing is host-side bookkeeping only: a freed slot
                # stops writing (done), and its cache/buffer regions are
                # overwritten by the next occupant per the module
                # invariants.
                self._active[b] = False
                self._done[b] = True
                self._unpin_slot_prefix(b)
                self._slot_req[b] = None
                return True
        return False

    def partial(self, request_id: int) -> Optional[np.ndarray]:
        """Streaming read: the tokens of an IN-FLIGHT request written so
        far (prompt included, truncated after a generated eos), as of
        the last chunk boundary.  None if the request is still queued or
        already completed (use :meth:`results` for completed ones).
        Finished slots are harvested first so a request never shows up
        both here and in ``results``."""
        self._check_usable()   # a streaming read touches device buffers
        self._harvest()
        for b in range(self._slots):
            req = self._slot_req[b]
            if req is not None and req.request_id == request_id:
                return self._slot_tokens(b)
        return None

    def _slot_tokens(self, b: int) -> np.ndarray:
        """Tokens written so far for slot ``b`` (shared by partial reads
        and harvest): absolute positions ``start..min(end, tick+1)``
        gathered from their ring images, truncated after the first eos
        GENERATED (not prompt-resident).  Pulls ONE fixed-shape row of
        the device-resident buffer (one compiled slice per slot index;
        variable bounds are applied in numpy so streaming polls don't
        accrete jit-cache entries)."""
        s, pe, e = int(self._start[b]), int(self._p_end[b]), \
            int(self._end[b])
        written = min(e, self._tick + 1)
        if self._pull_row is not None:   # cross-process slot row
            row = np.array(self._pull_row(self._tokens, jnp.int32(b)))
        else:
            row = np.array(self._tokens[b])
        seq = row[(s + np.arange(written - s)) % self._window]
        eos = int(self._eos[b])        # the slot's own (per-request) eos
        if eos >= 0:
            gen = seq[pe - s:]
            hits = np.nonzero(gen == eos)[0]
            if hits.size:
                seq = seq[:pe - s + hits[0] + 1]
        return seq

    # ------------------------------------------------------------------
    # scheduler internals
    # ------------------------------------------------------------------
    def _schedule(self) -> bool:
        """Harvest finished slots, admit queued requests (FIFO — in ring
        mode a free slot admits at ANY tick, so no fit check and no
        window reset exist).  True if a chunk should run.  Loops
        internally because a prefill admission can finish a request
        outright (max_new_tokens=1, or eos as the first token): such
        slots are harvested and refilled without running a chunk."""
        self._rebase_tick()
        while True:
            self._harvest()
            self._admit()
            # Finished-at-admission slots FIRST: free + refill them now,
            # before ticking, even while other slots are live — otherwise
            # a done slot would sit occupied through a whole chunk.
            if np.any(self._active & self._done):
                continue
            if np.any(self._active & ~self._done):
                return True
            # Pool fully idle (a free slot always admits, so an empty
            # pool means an empty queue): rewind to 0 — free (no state
            # moves; ring contents are occupant-masked).
            self._tick = 0
            return False

    _REBASE_AT = 1 << 24   # well under int32, amortized to ~never

    def _rebase_tick(self) -> None:
        """Bound absolute-tick growth under SUSTAINED load (the idle
        rewind never fires then): subtract a multiple of ``window`` from
        the tick and every slot's start/p_end/end.  Ring positions are
        ``x % window`` and masks/offsets are differences, so a shift
        that is ≡ 0 (mod window) is invisible to the device programs —
        pure host bookkeeping, O(slots), amortized to ~one shift per
        16M ticks."""
        if self._tick < self._REBASE_AT:
            return
        shift = (self._tick // self._window) * self._window
        self._tick -= shift
        self._start -= shift
        self._p_end -= shift
        self._end -= shift
        # Inactive slots' bounds are dead state (never consumed until the
        # next admission overwrites them) but would otherwise accumulate
        # -shift per rebase — a silent int32 wrap after ~2^31 total ticks
        # on a slot that never re-admits.  Zero them instead.
        inactive = ~self._active
        self._start[inactive] = 0
        self._p_end[inactive] = 0
        self._end[inactive] = 0

    def _prefix_compatible(self, req: Request) -> bool:
        """True when admitting ``req`` now keeps the one-live-prefix
        invariant: either no prefix generation is active, or ``req``
        pinned exactly that generation."""
        return (not req.use_prefix
                or self._active_prefix is None
                or req.prefix is self._active_prefix)

    def _pin_active_prefix(self, req: Request) -> None:
        if req.use_prefix:
            self._active_prefix = req.prefix
            self._active_prefix_users += 1

    def _unpin_slot_prefix(self, b: int) -> None:
        if self._use_prefix[b]:
            self._use_prefix[b] = False
            self._active_prefix_users -= 1
            if self._active_prefix_users <= 0:
                self._active_prefix_users = 0
                self._active_prefix = None   # last reader: KV now free

    def _admit(self) -> None:
        prefills: List[tuple] = []        # deferred (slot, req) pairs
        for b in range(self._slots):
            if self._active[b] or not self._queue:
                continue
            if not self._prefix_compatible(self._queue[0]):
                # Strict FIFO: the head pinned a different prefix
                # generation than the active readers'; it (and everyone
                # behind it) waits until the last old-generation reader
                # finishes.
                break
            req = self._queue.pop(0)      # FIFO: head always fits
            self._pin_active_prefix(req)
            req.t_admit = time.monotonic()
            p = req.prompt.size
            t0 = self._tick
            if self._prefill:
                # Deferred: this boundary's prefill admissions run as
                # ONE batched program (MXU-batched, one dispatch).  The
                # prompt lands BEHIND the tick at ring positions
                # (t0-P..t0-1) % window — valid even at t0 < P (the
                # slot's start tick goes negative; all position
                # arithmetic is by offset).
                prefills.append((b, req))
                continue
            # Sequential (teacher-forced) admission — the prefill=False
            # mode only (ring admission prefills unconditionally): the
            # prompt lands AHEAD of the tick and is consumed tick by
            # tick.
            try:
                self._tokens = _write_prompt_program(
                    self._tokens, self._pad_bucket(req.prompt),
                    np.int32(b), np.int32(t0))
            except Exception:
                self._poisoned = True   # tokens buffer was donated
                raise
            self._start[b] = t0
            self._p_end[b] = t0 + p
            self._end[b] = t0 + p + req.max_new_tokens
            self._done[b] = False
            self._active[b] = True
            self._temp[b] = req.temperature
            self._eos[b] = req.eos_id
            self._use_prefix[b] = req.use_prefix
            self._slot_req[b] = req
            self.stats.prompt_tokens += p
            self.stats.prefix_admissions += int(req.use_prefix)
        if prefills:
            self._flush_prefills(prefills)

    def _flush_prefills(self, group) -> None:
        """Run the boundary's prefill admissions in few, compile-bounded
        dispatches.  Rows group by their OWN pow-2 prompt bucket (a
        short prompt never pays a long prompt's padded O(Pb²) attention)
        and each bucket dispatches in pow-2-sized sub-batches; the slot
        fan-out S is pow-2 padded inside _run_prefill — so all three
        compile dimensions (Pb, K, S) are bucketed and the compiled
        program set stays logarithmic in window and slots."""
        buckets: Dict[tuple, Dict[bytes, list]] = {}
        for b, req in group:
            pb = self._prompt_bucket(req.prompt.size)
            # dedup identical prompts within a bucket: computed once,
            # K/V scattered to every requesting slot.  Prefix users
            # dispatch separately (their forward attends the shared
            # prefix and their positions are offset — a static program
            # difference).  Wrapness is likewise static (it selects the
            # contiguous-DUS vs mod-window-scatter cache write), decided
            # here with the same arithmetic the program uses; identical
            # prompts share a length, so dedup is unaffected.
            s0 = (self._tick - req.prompt.size) % self._window
            wrapped = s0 + pb > self._window
            buckets.setdefault((pb, req.use_prefix, wrapped), {}).setdefault(
                req.prompt.tobytes(), []).append((b, req))
        for (pb, with_prefix, wrapped), uniq in sorted(buckets.items()):
            entries = list(uniq.values())     # [[(b, req), ...], ...]
            while entries:
                k = 1 << (len(entries).bit_length() - 1)  # pow2 <= len
                self._run_prefill(entries[:k], pb, with_prefix, wrapped)
                entries = entries[k:]

    def _run_prefill(self, entries, pb: int, with_prefix: bool,
                     wrapped: bool = False) -> None:
        """One batched prefill dispatch over K unique prompts serving S
        slots (S >= K when prompts repeat): prompt K/V written at cache
        positions t0-P..t0-1 per slot and each first generated token
        deposited at the admission tick, so the slots start in
        generation phase.  ``with_prefix`` rows attend the shared
        cached prefix during their forward.  ``wrapped`` rows' ring
        ranges cross the window boundary and take the scatter cache
        write; all others take the contiguous fast path."""
        t0, k = self._tick, len(entries)
        prompts = np.zeros((k, pb), np.int32)
        p_lens = np.zeros(k, np.int32)
        slot_ids, row_map, flat = [], [], []
        for i, slot_reqs in enumerate(entries):
            prompt = slot_reqs[0][1].prompt
            prompts[i, :prompt.size] = prompt
            p_lens[i] = prompt.size
            for b, req in slot_reqs:
                slot_ids.append(b)
                row_map.append(i)
                flat.append((b, req))
                # per-slot knobs must land BEFORE the dispatch: the
                # program samples each slot's first token through them
                self._temp[b] = req.temperature
                self._eos[b] = req.eos_id
                self._use_prefix[b] = req.use_prefix
        slot_ids = np.asarray(slot_ids, np.int32)
        row_map = np.asarray(row_map, np.int32)
        # Pad S to its pow-2 bucket by repeating the last entry (an
        # idempotent duplicate write; the program reports landed buffer
        # values so duplicate sampling stays consistent) — S is a
        # compile dimension like K and Pb, and all three must be
        # bucketed to keep the compiled program set small.
        s_real = len(flat)
        s_pad = 1 << (s_real - 1).bit_length()
        if s_pad != s_real:
            slot_ids = np.concatenate(
                [slot_ids, np.full(s_pad - s_real, slot_ids[-1],
                                   np.int32)])
            row_map = np.concatenate(
                [row_map, np.full(s_pad - s_real, row_map[-1],
                                  np.int32)])
        self._rng, sub = jax.random.split(self._rng)
        try:
            knobs, kp, vp = self._dispatch_args(with_prefix)
            self._tokens, self._kc, self._vc, toks = _prefill_program(
                knobs, with_prefix, not wrapped, self._params,
                self._tokens, self._kc, self._vc, jnp.asarray(prompts),
                jnp.asarray(slot_ids), jnp.asarray(row_map),
                np.int32(t0), jnp.asarray(p_lens),
                jnp.asarray(self._temp), kp, vp, sub)
            if self._replicate is not None:
                toks = self._replicate(toks)
            toks = np.array(toks)
        except Exception:
            self._poisoned = True
            raise
        for j, (b, req) in enumerate(flat):
            p = req.prompt.size
            tok = int(toks[j])
            self._start[b] = t0 - p
            self._p_end[b] = t0
            self._end[b] = t0 + req.max_new_tokens
            self._done[b] = (req.max_new_tokens == 1
                             or (req.eos_id >= 0
                                 and tok == req.eos_id))
            self._active[b] = True
            self._slot_req[b] = req
            self.stats.prompt_tokens += p
            self.stats.prefilled_tokens += p
            self.stats.prefill_admissions += 1
            self.stats.prefix_admissions += int(req.use_prefix)
        self.stats.prefill_dedup_hits += len(flat) - k
        self.stats.prefill_dispatches += 1

    def _dispatch_args(self, with_prefix: bool):
        """(knobs, kp, vp) for one compiled-program dispatch — the ONE
        place encoding the compile-cache-key contract: prefix-touching
        dispatches carry the ACTIVE readers' pinned plen + K/V (which
        may be an older generation than the currently registered
        prefix — the mid-flight-swap guarantee), all others the plen=0
        knobs + dummies so their cache key is independent of any
        registered prefix."""
        if with_prefix:
            kp, vp, plen = self._active_prefix
            return (self._top_k, self._top_p, plen), kp, vp
        return (self._top_k, self._top_p, 0), self._kp0, self._kp0

    def _prompt_bucket(self, prompt_size: int) -> int:
        """Pow-2 compile bucket for a prompt, falling back to the exact
        size when the bucket would exceed the window (``Pb <= window``
        is the ring-safety bound: it keeps a bucket's pad tail off the
        prompt it pads).  The single definition of the bucketing rule —
        the batched (_flush_prefills) and sequential (_pad_bucket)
        admission paths must never desynchronize on it."""
        pb = 1 << (prompt_size - 1).bit_length()
        if pb > self._window:
            pb = prompt_size
        return pb

    def _pad_bucket(self, prompt: np.ndarray) -> jax.Array:
        """Zero-pad ``prompt`` to its pow-2 compile bucket (see
        :meth:`_prompt_bucket`)."""
        p = prompt.size
        padded = np.zeros(self._prompt_bucket(p), np.int32)
        padded[:p] = prompt
        return jnp.asarray(padded)

    def _harvest(self) -> None:
        for b in range(self._slots):
            if not (self._active[b] and self._done[b]):
                continue
            req = self._slot_req[b]
            s, pe = self._start[b], self._p_end[b]
            seq = self._slot_tokens(b)
            self.stats.generated_tokens += max(seq.size - (pe - s), 0)
            self.stats.completed += 1
            self._results[req.request_id] = seq
            self._active[b] = False
            self._unpin_slot_prefix(b)
            self._slot_req[b] = None
            wall = time.monotonic() - getattr(req, "t_admit", 0.0)
            if 0.0 < wall < 3600.0:
                self._avg_request_s = (0.8 * self._avg_request_s
                                       + 0.2 * wall
                                       if self._avg_request_s else wall)

    def _run_chunk(self) -> None:
        n = self._chunk       # ring: no window clamp (writes wrap)
        if self._queue:
            # Work is waiting: stop the chunk at the next KNOWN slot
            # retirement (its end bound — tick end[b]-2 finishes slot b)
            # so the freed slot refills immediately instead of idling to
            # the boundary.  eos stops stay unpredictable; this clamps
            # only on the exact bound.  The clamp is quantized DOWN to a
            # power of two: each distinct scan length is its own XLA
            # compile, so exact clamping could cost `chunk` compiles on
            # a cold cache — pow-2 sizes bound that at log2(chunk)+1
            # (undershooting just lands an extra boundary, never idles).
            live = self._active & ~self._done
            if live.any():
                nxt = int(self._end[live].min()) - 1 - self._tick
                if 0 < nxt < n:
                    n = 1 << (nxt.bit_length() - 1)
        self._rng, sub = jax.random.split(self._rng)
        try:
            # When no ACTIVE slot uses the prefix, run the plain program
            # (see _dispatch_args); both variants compile once.
            knobs, kp, vp = self._dispatch_args(
                bool(np.any(self._use_prefix & self._active)))
            self._tokens, self._kc, self._vc, done, busy = _chunk_program(
                n, knobs, self._params, self._tokens,
                self._kc, self._vc, jnp.asarray(self._start),
                jnp.asarray(self._p_end), jnp.asarray(self._end),
                jnp.asarray(self._done), jnp.asarray(self._active),
                jnp.asarray(self._temp), jnp.asarray(self._eos),
                jnp.asarray(self._use_prefix), kp, vp,
                jnp.int32(self._tick), sub)
            # The only per-chunk host pull: the [B] done vector (the
            # token buffer stays on device; harvest/partial pull rows).
            if self._replicate2 is not None:
                done, busy = self._replicate2(done, busy)
            self._done = np.array(done)
        except Exception:
            self._poisoned = True
            raise
        self._tick += n
        self.stats.ticks += n
        self.stats.busy_slot_ticks += int(busy)
        self.stats.chunks += 1
