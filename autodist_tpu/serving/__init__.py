"""Serving-side subsystem: continuous-batching decode engine.

Beyond the reference (training-only — its serving story ends at
``SavedModelBuilder`` export, reference ``autodist/checkpoint/
saved_model_builder.py:24-64``): a slot-based continuous-batching
engine over the KV-cache decode path of ``models/generate.py``.
"""
from autodist_tpu.serving.engine import DecodeEngine, EngineStats, Request

__all__ = ["DecodeEngine", "EngineStats", "Request"]
