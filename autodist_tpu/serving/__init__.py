"""Serving-side subsystem: continuous-batching engines + HTTP front +
supervised replica routing.

Beyond the reference (training-only — its serving story ends at
``SavedModelBuilder`` export, reference ``autodist/checkpoint/
saved_model_builder.py:24-64``):

* the slot-based continuous-batching :class:`DecodeEngine` over the
  KV-cache decode path of ``models/generate.py``;
* the paged-KV scale-out stack — :mod:`~autodist_tpu.serving.paged_kv`
  (block pool, refcounted COW prefix trie, paged device programs) and
  :class:`PagedDecodeEngine` (SLO-class bounded queues, block-budget
  admission, chunked prefill, immediate slot recycling);
* a stdlib HTTP server (completions + SSE streaming + cancel + stats +
  Prometheus ``/metrics``) in front of either engine;
* the :class:`Router` + :class:`SupervisedReplicaPool` pair: N
  replicas supervised through the PR 4 resilience machinery, with
  queue-depth/block-headroom load balancing, re-routing of in-flight
  requests when a replica dies, token-exact mid-decode recovery,
  per-replica circuit breakers, graceful drain (``/admin/drain`` +
  SIGTERM, ``rolling_restart()``), deadline shedding
  (:class:`DeadlineError` → 503), and optional hedging
  (docs/serving.md, "Fault tolerance").
"""
from autodist_tpu.serving.engine import (AdmissionError, DeadlineError,
                                         DecodeEngine, EngineStats,
                                         Request)
from autodist_tpu.serving.paged_kv import (BlockPool, BlockPoolExhausted,
                                           PrefixTrie)
from autodist_tpu.serving.scheduler import (PagedDecodeEngine,
                                            SLO_CLASSES, SLO_LATENCY,
                                            SLO_THROUGHPUT)
from autodist_tpu.serving.router import (Router, RouterBusy,
                                         RouterDeadlineError, RouterError,
                                         RouterRequestError,
                                         SupervisedReplicaPool)
from autodist_tpu.serving.server import (EngineServer,
                                         install_drain_on_sigterm, serve)

__all__ = ["AdmissionError", "DeadlineError", "DecodeEngine",
           "EngineStats", "Request",
           "BlockPool", "BlockPoolExhausted", "PrefixTrie",
           "PagedDecodeEngine", "SLO_CLASSES", "SLO_LATENCY",
           "SLO_THROUGHPUT", "Router", "RouterBusy",
           "RouterDeadlineError", "RouterError",
           "RouterRequestError", "SupervisedReplicaPool", "EngineServer",
           "install_drain_on_sigterm", "serve"]
