"""Serving-side subsystem: continuous-batching decode engine + HTTP front.

Beyond the reference (training-only — its serving story ends at
``SavedModelBuilder`` export, reference ``autodist/checkpoint/
saved_model_builder.py:24-64``): a slot-based continuous-batching
engine over the KV-cache decode path of ``models/generate.py``, and a
stdlib HTTP server (completions + SSE streaming + cancel + stats) in
front of it.
"""
from autodist_tpu.serving.engine import DecodeEngine, EngineStats, Request
from autodist_tpu.serving.server import EngineServer, serve

__all__ = ["DecodeEngine", "EngineStats", "Request", "EngineServer",
           "serve"]
