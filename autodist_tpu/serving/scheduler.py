"""Continuous-batching scheduler over the paged KV cache.

This is the serving-side analog of the training stack's "schedule as an
explicit program over shared resources" move: admission is decoupled
from slots, memory is a :class:`~autodist_tpu.serving.paged_kv.BlockPool`
instead of slot-shaped regions, and every scheduling decision is host
bookkeeping over explicit queues and block tables — the device programs
never see a request boundary.

:class:`PagedDecodeEngine` composes the pieces:

* **Bounded SLO queues.**  ``submit(..., slo=)`` lands a request in its
  class's bounded FIFO (``"latency"`` drains strictly before
  ``"throughput"``); a full queue raises the typed
  :class:`~autodist_tpu.serving.engine.AdmissionError` with a
  ``Retry-After`` hint instead of ballooning host memory.
* **Block-budget admission.**  A request is admitted only when a slot
  AND its whole worst-case span's blocks are available (after trie
  lookup and, under pressure, LRU eviction of unpinned cached blocks),
  keeping ``reserve_blocks`` free as a watermark — so decode can never
  OOM mid-step: every admitted request's blocks are pre-reserved.
  An unfittable request stays queued (deferred, counted) until frees
  or eviction make room; one that could NEVER fit is rejected at
  submit.
* **Prefix reuse.**  The prompt's longest trie-cached full-block chain
  is referenced, not recomputed: prefill covers only the suffix,
  attending the cached blocks through the request's own block table.
* **Chunked prefill.**  Long prompts charge in ``prefill_chunk``-token
  pieces interleaved with decode chunks, so one long admission cannot
  stall the decode batch for its whole prompt (the cached-context mask
  that enables prefix reuse is the same mechanism — see
  ``_paged_prefill_program``).
* **Immediate slot recycling.**  Harvest frees a finished request's
  slot and returns its non-shared blocks to the pool in the same
  boundary; the next admission reuses both without any drain.
* **Speculative decoding as a first-class mode.**  Constructed with a
  ``draft_spec``/``draft_params`` pair, the engine replaces per-token
  decode ticks with draft-and-verify rounds: the draft model proposes
  ``gamma`` tokens through its OWN paged K/V (draft pages come from
  the SAME :class:`BlockPool` — admission pre-reserves both spans, and
  COW/trie/eviction rules are unchanged because draft blocks are
  request-private and never trie-shared), and the target verifies all
  gamma+1 candidates in one ``_paged_prefill_program`` dispatch — the
  ``n_shared`` cached-context mask makes multi-token verify the SAME
  traced program as chunked prefill.  Greedy acceptance keeps the
  output token-exact vs the target-only oracle regardless of draft
  quality.  ``gamma`` adapts to SLO pressure every round: it shrinks
  toward 1 when the latency-class queue backs up or free slots vanish,
  regrows when slots idle, and an acceptance-length EWMA caps it so a
  badly-mismatched draft degrades gracefully toward plain decode
  instead of wasting verify bandwidth (docs/serving.md).

Greedy output is token-exact vs the per-request ``generate`` oracle and
vs the slot engine — including requests admitted mid-run — pinned in
``tests/test_serving_scheduler.py`` (speculative mode:
``tests/test_spec_serving.py``).
"""
from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from autodist_tpu.models.base import ModelSpec
from autodist_tpu.models.generate import (_vocab_size, check_sampling_args,
                                          require_lm_spec)
from autodist_tpu.serving.engine import (AdmissionError, DeadlineError,
                                         TEMPERATURE_FLOOR,
                                         _sharded_zeros,
                                         _write_prompt_program,
                                         check_speculative_args)
from autodist_tpu.serving.paged_kv import (SCRATCH_BLOCK, BlockPool,
                                           BlockPoolExhausted, PrefixTrie,
                                           _commit_tokens_program,
                                           _paged_chunk_program,
                                           _paged_prefill_program)

#: SLO classes, in strict admission-priority order.
SLO_LATENCY = "latency"
SLO_THROUGHPUT = "throughput"
SLO_CLASSES = (SLO_LATENCY, SLO_THROUGHPUT)


@dataclass
class PagedRequest:
    """One request's full scheduler lifecycle: queued -> (slot +
    blocks) -> chunked prefill -> decode -> harvested."""
    prompt: np.ndarray
    max_new_tokens: int
    request_id: int
    slo: str
    temperature: float
    eos_id: int
    strip: int = 0                 # leading tokens dropped from result
    # Propagated trace id (router -> replica HTTP header -> here): the
    # queue-wait/prefill/decode spans recorded at harvest carry it, so
    # one request's spans correlate across hosts in the exported trace
    # (docs/observability.md).
    trace_id: str = ""
    submit_t: float = 0.0
    # Absolute monotonic completion deadline (None = unbounded).  The
    # step boundary cancels a past-deadline request wherever it sits —
    # queued, prefilling or decoding — and frees its blocks immediately
    # (docs/serving.md "Fault tolerance").
    deadline_t: Optional[float] = None
    admit_t: Optional[float] = None
    first_token_t: Optional[float] = None
    done_t: Optional[float] = None
    n_cached: int = 0              # trie-matched prompt tokens
    blocks: List[int] = field(default_factory=list)
    slot: Optional[int] = None
    charged: int = 0               # prompt tokens whose K/V are in pool
    # Speculative-mode lifecycle (unused on a target-only engine):
    gamma: int = 0                 # per-request proposal-depth cap
    draft_blocks: List[int] = field(default_factory=list)
    draft_charged: int = 0         # prompt tokens in the DRAFT's pages
    spec_rounds: int = 0           # draft-and-verify rounds this request
    spec_proposed: int = 0         # draft tokens proposed
    spec_accepted: int = 0         # draft tokens accepted
    spec_bonus: int = 0            # target bonus tokens committed
    # Cumulative wall time of the two round windows, dispatch-side
    # attribution: draft and verify queue back-to-back on the device
    # stream with one host sync at the end of verify, so the draft
    # window covers its dispatch and the verify window includes the
    # sync + acceptance.
    draft_s: float = 0.0
    verify_s: float = 0.0


@dataclass
class PagedEngineStats:
    """Aggregate scheduler counters (monotonic over engine lifetime)."""
    submitted: int = 0
    completed: int = 0
    rejected_full: int = 0         # AdmissionError raises (queue full)
    shed_deadline: int = 0         # DeadlineError raises (infeasible)
    expired_deadline: int = 0      # in-flight/queued deadline cancels
    deferred_blocks: int = 0       # admission waits on pool headroom
    ticks: int = 0
    busy_slot_ticks: int = 0
    chunks: int = 0                # decode-program dispatches
    prefill_dispatches: int = 0    # prefill-program dispatches
    prefill_chunks: int = 0        # request-chunks charged
    generated_tokens: int = 0
    prompt_tokens: int = 0
    cached_prompt_tokens: int = 0  # prompt tokens served from the trie
    prefix_requests: int = 0       # requests with >= 1 cached block
    spec_rounds: int = 0           # per-request draft-and-verify rounds
    draft_prefill_dispatches: int = 0
    draft_tokens_proposed: int = 0
    draft_tokens_accepted: int = 0
    bonus_tokens: int = 0          # target tokens at the first mismatch

    _slots: int = field(default=0, repr=False)

    @property
    def acceptance_rate(self) -> float:
        """Fraction of draft proposals the target's argmax confirmed."""
        return (self.draft_tokens_accepted / self.draft_tokens_proposed
                if self.draft_tokens_proposed else 0.0)

    @property
    def mean_accept_len(self) -> float:
        """Mean accepted draft tokens per verify round (excludes the
        always-committed bonus token)."""
        return (self.draft_tokens_accepted / self.spec_rounds
                if self.spec_rounds else 0.0)

    @property
    def slot_utilization(self) -> float:
        total = self.ticks * self._slots if self._slots else 0
        return self.busy_slot_ticks / total if total else 0.0

    @property
    def prefix_hit_rate(self) -> float:
        """Fraction of prompt tokens whose prefill was skipped."""
        return (self.cached_prompt_tokens / self.prompt_tokens
                if self.prompt_tokens else 0.0)


def _p90(samples) -> float:
    """90th percentile of a small sample deque (nearest-rank)."""
    s = sorted(samples)
    return s[min(int(0.9 * len(s)), len(s) - 1)]


def _pow2_bucket(n: int, cap: int) -> int:
    """Pow-2 compile bucket capped at ``cap`` (exact-size fallback) —
    the slot engine's bucketing rule over an explicit cap."""
    pb = 1 << (n - 1).bit_length()
    return pb if pb <= cap else n


class PagedDecodeEngine:
    """Continuous-batching decode over a paged, prefix-shared KV pool.

    Usage mirrors :class:`~autodist_tpu.serving.engine.DecodeEngine`::

        eng = PagedDecodeEngine(spec, params, slots=8, window=256,
                                block_size=32, num_blocks=128)
        rid = eng.submit(prompt_1d, max_new_tokens=64, slo="latency")
        results = eng.run()          # {rid: np.ndarray tokens}

    ``window`` is the per-request span cap (``prompt + max_new``), a
    multiple of ``block_size``; ``num_blocks`` sizes the shared pool
    (defaults to every slot full plus one request's worth of cache
    slack).  ``mesh`` shards the pool and every per-tick einsum over
    the model (TP) axis — per-head attention has no cross-head math, so
    GSPMD runs each head group on its own devices.

    The compiled programs live at module scope (``paged_kv``), so
    engine rebuilds re-trace nothing an earlier instance compiled.
    """

    def __init__(self, spec: ModelSpec, params, *, slots: int = 8,
                 window: int = 256, block_size: int = 32,
                 num_blocks: Optional[int] = None, chunk: int = 16,
                 prefill_chunk: Optional[int] = None,
                 max_queue: int = 64, reserve_blocks: int = 0,
                 cache_prefixes: bool = True, temperature: float = 0.0,
                 top_k: int = 0, top_p: float = 0.0,
                 eos_id: Optional[int] = None,
                 rng: Optional[jax.Array] = None, mesh=None,
                 model_axis: str = "model",
                 draft_spec: Optional[ModelSpec] = None,
                 draft_params=None, gamma: int = 4,
                 adapt_gamma: bool = True,
                 deadline_defaults: Optional[Dict[str, float]] = None):
        require_lm_spec(spec, "PagedDecodeEngine")
        cfg = spec.config
        if slots < 1 or chunk < 1:
            raise ValueError("need slots >= 1 and chunk >= 1")
        if block_size < 1 or window < 2 * block_size:
            raise ValueError("need block_size >= 1 and window >= "
                             "2 * block_size")
        if window % block_size:
            raise ValueError(f"window={window} must be a multiple of "
                             f"block_size={block_size}")
        if window > cfg["max_len"]:
            raise ValueError(
                f"window={window} exceeds the model's max_len "
                f"{cfg['max_len']} (pos_embed rows)")
        if prefill_chunk is not None and prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1 (or None)")
        if max_queue < 1:
            raise ValueError("max_queue must be >= 1")
        self._maxb = window // block_size
        if num_blocks is None:
            num_blocks = slots * self._maxb + self._maxb + 1
        if num_blocks < self._maxb + 1 + reserve_blocks:
            raise ValueError(
                f"num_blocks={num_blocks} cannot hold one full-window "
                f"request ({self._maxb} blocks) plus the scratch block "
                f"and reserve_blocks={reserve_blocks}")
        vocab = _vocab_size(params)
        check_sampling_args(vocab, temperature, top_k, top_p, eos_id, rng)
        if (draft_spec is None) != (draft_params is None):
            raise ValueError("draft_spec and draft_params must be "
                             "passed together")
        if draft_spec is not None:
            require_lm_spec(draft_spec, "PagedDecodeEngine draft")
            dcfg = draft_spec.config
            if dcfg["vocab_size"] != cfg["vocab_size"]:
                raise ValueError(
                    f"target/draft vocab mismatch: {cfg['vocab_size']} "
                    f"vs {dcfg['vocab_size']}")
            if window > dcfg["max_len"]:
                raise ValueError(
                    f"window={window} exceeds the draft model's "
                    f"max_len {dcfg['max_len']}")
            # Engine-level knob validation mirrors submit's per-request
            # rule: speculation is greedy-acceptance, target-exact only
            # at temperature 0.
            check_speculative_args(gamma, temperature)

        self._spec = spec
        self._params = params
        self._cfg = cfg
        self._slots = slots
        self._window = window
        self._block_size = block_size
        self._num_blocks = int(num_blocks)
        self._chunk = chunk
        self._prefill_chunk = prefill_chunk
        self._max_queue = int(max_queue)
        self._reserve = int(reserve_blocks)
        if deadline_defaults is not None:
            bad = set(deadline_defaults) - set(SLO_CLASSES)
            if bad:
                raise ValueError(
                    f"deadline_defaults keys must be SLO classes "
                    f"{SLO_CLASSES}; got {sorted(bad)}")
            if any(float(v) <= 0 for v in deadline_defaults.values()):
                raise ValueError("deadline_defaults values must be > 0")
        self._deadline_defaults = {
            k: float(v) for k, v in (deadline_defaults or {}).items()}
        self._cache_prefixes = bool(cache_prefixes)
        self._temperature = float(temperature)
        self._top_k = int(top_k)
        self._top_p = float(top_p)
        self._eos_id = -1 if eos_id is None else int(eos_id)
        self._rng = rng if rng is not None else jax.random.PRNGKey(0)
        self._rng_explicit = rng is not None
        self._vocab = vocab
        self._mesh = mesh
        self._model_axis = model_axis
        if mesh is not None and model_axis not in mesh.axis_names:
            raise ValueError(f"model_axis {model_axis!r} not in mesh "
                             f"axes {mesh.axis_names}")

        self._draft_spec = draft_spec
        self._draft_params = draft_params
        self._gamma_max = int(gamma)
        self._adapt_gamma = bool(adapt_gamma)
        self._gamma = self._gamma_max        # SLO-adapted, in [1, max]
        self._accept_ewma = float(self._gamma_max)  # optimistic start
        self._gamma_hist: Dict[int, int] = {}
        self._draft_blocks_live = 0

        self._knobs = (self._top_k, self._top_p, block_size)
        self._queues: Dict[str, Deque[PagedRequest]] = {
            c: deque() for c in SLO_CLASSES}
        self._next_id = 0
        self._results: Dict[int, np.ndarray] = {}
        self._timings: Dict[int, Dict[str, float]] = {}
        self._slot_req: List[Optional[PagedRequest]] = [None] * slots
        self._prefilling: Dict[int, PagedRequest] = {}
        self._prefix_tokens: Optional[np.ndarray] = None
        self._avg_request_s = 0.0
        # Measured service-rate samples feeding the deadline-shed
        # estimate: queue-wait (submit -> admit) and per-token decode
        # time, both from completed requests.  Bounded deques — recent
        # load, not lifetime averages.
        self._qwait_samples: Deque[float] = deque(maxlen=128)
        self._per_tok_samples: Deque[float] = deque(maxlen=256)
        self._expired: Dict[int, Dict[str, object]] = {}
        self._poisoned = False
        self.stats = PagedEngineStats(_slots=slots)
        self.pool = BlockPool(self._num_blocks, block_size)
        self.trie = PrefixTrie(self.pool) if cache_prefixes else None
        self._alloc_state()

    # ------------------------------------------------------------------
    # state allocation
    # ------------------------------------------------------------------
    def _alloc_state(self) -> None:
        slots, w, cfg = self._slots, self._window, self._cfg
        self._tokens = self._kc = self._vc = None   # drop before realloc
        self._dkc = self._dvc = None
        self._start = np.zeros(slots, np.int32)
        self._p_end = np.zeros(slots, np.int32)
        self._end = np.zeros(slots, np.int32)
        self._done = np.ones(slots, bool)
        self._active = np.zeros(slots, bool)
        self._temp = np.full(slots, self._temperature, np.float32)
        self._eos = np.full(slots, self._eos_id, np.int32)
        self._bt = np.full((slots, self._maxb), SCRATCH_BLOCK, np.int32)
        # Speculative-mode state: the draft's block table (draft pages
        # come from the same pool, so the table has the same shape),
        # per-slot committed-token counts (spec rounds advance by a
        # variable amount — the tick no longer measures progress), and
        # the adaptation state.
        self._dbt = np.full((slots, self._maxb), SCRATCH_BLOCK, np.int32)
        self._committed = np.zeros(slots, np.int32)
        self._gamma = self._gamma_max
        self._accept_ewma = float(self._gamma_max)
        self._gamma_hist = {}
        self._draft_blocks_live = 0
        self._tick = 0
        heads, hd = cfg["num_heads"], cfg["head_dim"]
        dtype = self._params["pos_embed"].dtype
        pool_shape = (cfg["num_layers"], self._num_blocks,
                      self._block_size, heads, hd)
        if self._draft_spec is not None:
            dcfg = self._draft_spec.config
            dpool_shape = (dcfg["num_layers"], self._num_blocks,
                           self._block_size, dcfg["num_heads"],
                           dcfg["head_dim"])
            ddtype = self._draft_params["pos_embed"].dtype
        if self._mesh is None:
            self._tokens = jnp.zeros((slots, w), jnp.int32)
            self._kc = jnp.zeros(pool_shape, dtype)
            self._vc = jnp.zeros(pool_shape, dtype)
            if self._draft_spec is not None:
                self._dkc = jnp.zeros(dpool_shape, ddtype)
                self._dvc = jnp.zeros(dpool_shape, ddtype)
        else:
            from jax.sharding import NamedSharding, PartitionSpec as P

            rep = NamedSharding(self._mesh, P())
            heads_sh = NamedSharding(
                self._mesh, P(None, None, None, self._model_axis))
            self._tokens = _sharded_zeros((slots, w), jnp.int32, rep)()
            self._kc = _sharded_zeros(pool_shape, dtype, heads_sh)()
            self._vc = _sharded_zeros(pool_shape, dtype, heads_sh)()
            if self._draft_spec is not None:
                self._dkc = _sharded_zeros(dpool_shape, ddtype,
                                           heads_sh)()
                self._dvc = _sharded_zeros(dpool_shape, ddtype,
                                           heads_sh)()

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------
    def reset(self) -> None:
        """Drop ALL state — queues, in-flight, unfetched results, the
        block pool and the prefix cache — and reallocate.  Revives a
        poisoned engine (module-scope jit cache: no recompiles)."""
        for q in self._queues.values():
            q.clear()
        self._results.clear()
        self._timings.clear()
        self._expired.clear()
        self._qwait_samples.clear()
        self._per_tok_samples.clear()
        self._slot_req = [None] * self._slots
        self._prefilling.clear()
        self.pool = BlockPool(self._num_blocks, self._block_size)
        self.trie = PrefixTrie(self.pool) if self._cache_prefixes else None
        self.stats = PagedEngineStats(_slots=self._slots)
        self._alloc_state()
        self._poisoned = False

    def _check_usable(self) -> None:
        if self._poisoned:
            raise RuntimeError(
                "PagedDecodeEngine is poisoned: a device dispatch "
                "failed after its state buffers were donated; in-flight "
                "requests are lost — reset() or rebuild the engine")

    def set_prefix(self, tokens) -> int:
        """Compatibility shim over the trie: registers a shared system
        prompt that ``submit(..., use_prefix=True)`` PREPENDS to the
        request's prompt (and strips from its result).  The trie then
        dedups its K/V across requests like any other shared prefix —
        no special storage, no idle requirement, and clearing frees
        nothing until the last reader's blocks are released."""
        self._check_usable()
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        if tokens.size < 1:
            raise ValueError("prefix must have at least one token")
        if not np.all((tokens >= 0) & (tokens < self._vocab)):
            raise ValueError("prefix tokens out of vocab range")
        if tokens.size + 2 > self._window:
            raise ValueError(
                f"prefix length {tokens.size} leaves no room in the "
                f"engine window {self._window}")
        self._prefix_tokens = tokens
        return int(tokens.size)

    def clear_prefix(self) -> None:
        self._check_usable()
        self._prefix_tokens = None

    @property
    def prefix_len(self) -> int:
        return 0 if self._prefix_tokens is None \
            else int(self._prefix_tokens.size)

    def submit(self, prompt, max_new_tokens: int, *,
               temperature: Optional[float] = None,
               eos_id: Optional[int] = None, slo: str = SLO_LATENCY,
               use_prefix: bool = False, trace_id: str = "",
               gamma: Optional[int] = None,
               deadline_s: Optional[float] = None) -> int:
        """Queue a request into its SLO class; returns its id.

        ``trace_id`` tags this request's queue-wait/prefill/decode
        spans in the telemetry span stream (propagated from the
        router's HTTP header by the server; empty = spans recorded
        untagged).  On a speculative engine ``gamma`` caps THIS
        request's proposal depth (default: the engine's ``gamma``);
        the SLO adaptation only ever shrinks below it.

        ``deadline_s`` bounds the request's whole lifetime (default:
        the engine's ``deadline_defaults`` for its SLO class, if any).
        Admission SHEDS a deadlined request the measured queue-wait /
        per-token percentiles say cannot finish in time — a typed
        :class:`DeadlineError` (503 + Retry-After at the HTTP front)
        instead of admitting work guaranteed to be thrown away; with
        no measurements yet the request is admitted optimistically.
        Past-deadline requests already admitted are cancelled at the
        next step boundary (blocks freed immediately, surfaced via
        :meth:`pop_expired`).

        Raises :class:`AdmissionError` (with ``retry_after_s``) when the
        class's queue is at ``max_queue``; raises ``ValueError`` for a
        request that could NEVER be admitted (span over the window, or
        more blocks than the pool minus the reserve can ever hold) and
        for speculation knobs that would fail mid-run — ``gamma < 1``,
        non-greedy temperature, or the span plus gamma slack
        overflowing the window are all rejected HERE, mirroring the
        temperature-floor rule (``check_speculative_args``)."""
        self._check_usable()
        if slo not in SLO_CLASSES:
            raise ValueError(f"slo must be one of {SLO_CLASSES}, "
                             f"got {slo!r}")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must have at least one token")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if not np.all((prompt >= 0) & (prompt < self._vocab)):
            raise ValueError("prompt tokens out of vocab range")
        strip = 0
        if use_prefix:
            if self._prefix_tokens is None:
                raise ValueError("use_prefix=True but no prefix is "
                                 "registered (call set_prefix first)")
            strip = int(self._prefix_tokens.size)
            prompt = np.concatenate([self._prefix_tokens, prompt])
        span = prompt.size + int(max_new_tokens)
        if span > self._window:
            # (the window bound also caps the block need: the
            # constructor guarantees the pool can always hold one
            # full-window request past the reserve, so any admitted
            # span eventually fits)
            raise ValueError(
                f"prompt + max_new_tokens = {span} exceeds the engine "
                f"window {self._window}; raise window= or split")
        temperature, eos_id = self._check_knobs(temperature, eos_id)
        if self._draft_spec is None:
            if gamma is not None:
                raise ValueError(
                    "per-request gamma needs a speculative engine "
                    "(pass draft_spec/draft_params at construction)")
            gamma = 0
        else:
            gamma = self._gamma_max if gamma is None else int(gamma)
            check_speculative_args(gamma, temperature, span=span,
                                   window=self._window)
        if deadline_s is None:
            deadline_s = self._deadline_defaults.get(slo)
        elif float(deadline_s) <= 0:
            raise ValueError(f"deadline_s must be > 0, got {deadline_s}")
        q = self._queues[slo]
        if len(q) >= self._max_queue:
            self.stats.rejected_full += 1
            raise AdmissionError(
                f"{slo} queue full ({self._max_queue}); retry later",
                retry_after_s=self._retry_hint())
        if deadline_s is not None:
            est = self._deadline_estimate(int(max_new_tokens))
            if est is not None and est > float(deadline_s):
                self.stats.shed_deadline += 1
                from autodist_tpu.telemetry import emit_event
                emit_event("serving/shed", phase="admission", slo=slo,
                           deadline_s=float(deadline_s),
                           estimate_s=round(est, 4),
                           trace_id=str(trace_id or ""))
                raise DeadlineError(
                    f"cannot meet deadline_s={deadline_s:g}: estimated "
                    f"completion {est:.3f}s (measured queue-wait + "
                    f"{max_new_tokens} tokens at current rates)",
                    retry_after_s=self._retry_hint())
        req = PagedRequest(prompt, int(max_new_tokens), self._next_id,
                           slo=slo, temperature=temperature,
                           eos_id=eos_id, strip=strip,
                           trace_id=str(trace_id or ""),
                           submit_t=time.monotonic(), gamma=gamma)
        if deadline_s is not None:
            req.deadline_t = req.submit_t + float(deadline_s)
        self._next_id += 1
        q.append(req)
        self.stats.submitted += 1
        return req.request_id

    def _check_knobs(self, temperature, eos_id):
        """Per-request sampling-knob validation — the slot engine's
        rules (see ``DecodeEngine.submit``), shared semantics."""
        if temperature is None:
            temperature = self._temperature
        else:
            temperature = float(temperature)
            if not np.isfinite(temperature) or temperature < 0.0:
                raise ValueError(f"temperature must be a finite number "
                                 f">= 0, got {temperature}")
            if temperature > 0.0 and float(np.float32(temperature)) == 0.0:
                raise ValueError(f"temperature {temperature} underflows "
                                 f"float32; use 0 for greedy or >= 1e-6")
            if 0.0 < temperature < TEMPERATURE_FLOOR:
                raise ValueError(
                    f"temperature {temperature} is below the sampling "
                    f"floor {TEMPERATURE_FLOOR}; use 0 for greedy or "
                    f">= {TEMPERATURE_FLOOR}")
            if (temperature > 0.0 and self._temperature <= 0.0
                    and not self._rng_explicit):
                raise ValueError(
                    "per-request temperature sampling on a greedy-built "
                    "engine needs an explicit rng= at engine "
                    "construction")
        if eos_id is None:
            eos_id = self._eos_id
        else:
            eos_id = int(eos_id)
            if eos_id != -1 and not 0 <= eos_id < self._vocab:
                raise ValueError(f"eos_id must be -1 (none) or in [0, "
                                 f"{self._vocab}), got {eos_id}")
        return temperature, eos_id

    def _retry_hint(self) -> float:
        per_req = self._avg_request_s or 1.0
        depth = sum(len(q) for q in self._queues.values())
        est = (depth + 1) * per_req / max(self._slots, 1)
        return float(min(60.0, max(0.1, est)))

    _MIN_DEADLINE_SAMPLES = 5

    def _deadline_estimate(self, max_new: int) -> Optional[float]:
        """Estimated completion time for a fresh request: p90 measured
        queue wait + ``max_new`` tokens at the p90 measured per-token
        rate.  None (= admit optimistically) until both sample sets
        have :data:`_MIN_DEADLINE_SAMPLES` — shedding on guesses would
        reject the very requests that produce the measurements."""
        if len(self._qwait_samples) < self._MIN_DEADLINE_SAMPLES \
                or len(self._per_tok_samples) < self._MIN_DEADLINE_SAMPLES:
            return None
        return (_p90(self._qwait_samples)
                + max_new * _p90(self._per_tok_samples))

    def _expire_deadlines(self) -> None:
        """Step-boundary deadline sweep: cancel every past-deadline
        request wherever it sits (queued, prefilling, decoding), free
        its slot and blocks IMMEDIATELY, and record it for
        :meth:`pop_expired` — decoding tokens past their deadline only
        steals capacity from requests that can still make theirs."""
        now = time.monotonic()
        victims: List[tuple] = []
        for slo, q in self._queues.items():
            for req in list(q):
                if req.deadline_t is not None and now > req.deadline_t:
                    q.remove(req)
                    victims.append((req, "queued"))
        for b, req in list(self._prefilling.items()):
            if req.deadline_t is not None and now > req.deadline_t:
                del self._prefilling[b]
                self._free_slot(b, req)
                victims.append((req, "prefilling"))
        for b in range(self._slots):
            req = self._slot_req[b]
            if req is not None and req.deadline_t is not None \
                    and now > req.deadline_t:
                self._active[b] = False
                self._done[b] = True
                self._slot_req[b] = None
                self._free_slot(b, req)
                victims.append((req, "decoding"))
        if not victims:
            return
        from autodist_tpu.telemetry import emit_event
        for req, phase in victims:
            self.stats.expired_deadline += 1
            overrun = now - req.deadline_t
            emit_event("serving/shed", phase=phase, slo=req.slo,
                       request_id=req.request_id,
                       trace_id=req.trace_id,
                       overrun_s=round(overrun, 4))
            self._expired[req.request_id] = {
                "phase": phase, "slo": req.slo,
                "trace_id": req.trace_id,
                "overrun_s": overrun,
            }

    def pop_expired(self) -> Dict[int, Dict[str, object]]:
        """Requests the deadline sweep cancelled since the last call:
        ``{request_id: {"phase", "slo", "trace_id", "overrun_s"}}``.
        The HTTP front drains this to resolve their waiters (504 +
        Retry-After) instead of letting them ride to timeout."""
        out, self._expired = self._expired, {}
        return out

    def run(self) -> Dict[int, np.ndarray]:
        """Decode until queues, prefill and all slots drain; returns
        and clears ``{request_id: tokens}``."""
        self._check_usable()
        while self.step():
            pass
        out, self._results = self._results, {}
        return out

    def step(self) -> bool:
        """One scheduler boundary: harvest, admit, at most one prefill
        wave, one decode chunk.  False when fully drained."""
        self._check_usable()
        self._rebase_tick()
        self._expire_deadlines()
        self._harvest()
        self._admit()
        if self._prefilling:
            self._dispatch_prefills()
            # finished-at-admission requests (max_new=1 / first-token
            # eos) free + refill immediately, before any decode chunk;
            # requests with chunks left stay in _prefilling for later
            # boundaries, interleaved with the decode chunks below
            self._harvest()
            self._admit()
        if np.any(self._active & ~self._done):
            if self._draft_spec is not None:
                self._run_spec_round()
            else:
                self._run_chunk()
        if self._pending_work():
            return True
        self._harvest()
        if self._pending_work():
            return True
        self._tick = 0   # fully idle: free rewind (positions are
        #                  logical per-request; nothing references tick)
        return False

    def _pending_work(self) -> bool:
        return bool(self._prefilling
                    or any(self._queues.values())
                    or np.any(self._active))

    def results(self) -> Dict[int, np.ndarray]:
        """Completed results so far (and clears them)."""
        if not self._poisoned:
            self._harvest()
        out, self._results = self._results, {}
        return out

    def pop_timings(self) -> Dict[int, Dict[str, float]]:
        """Per-request latency samples for completed requests since the
        last call: ``queue_wait_s`` (submit -> admit), ``ttft_s``
        (submit -> first generated token landed) and ``per_token_s``
        (mean inter-token time after the first), plus ``generated``.
        The HTTP front feeds these into its fixed-bound histograms."""
        out, self._timings = self._timings, {}
        return out

    def cancel(self, request_id: int) -> bool:
        """Cancel a queued, prefilling or decoding request; frees its
        slot and blocks immediately.  False if unknown/completed."""
        for q in self._queues.values():
            for i, req in enumerate(q):
                if req.request_id == request_id:
                    del q[i]
                    return True
        for b, req in list(self._prefilling.items()):
            if req.request_id == request_id:
                del self._prefilling[b]
                self._free_slot(b, req)
                return True
        for b in range(self._slots):
            req = self._slot_req[b]
            if req is not None and req.request_id == request_id:
                self._active[b] = False
                self._done[b] = True
                self._slot_req[b] = None
                self._free_slot(b, req)
                return True
        return False

    def partial(self, request_id: int) -> Optional[np.ndarray]:
        """Streaming read of an in-flight DECODING request's tokens so
        far (strip applied, eos-truncated); None if queued, still
        prefilling, or completed."""
        self._check_usable()
        self._harvest()
        for b in range(self._slots):
            req = self._slot_req[b]
            if req is not None and req.request_id == request_id:
                return self._slot_tokens(b, req)
        return None

    def scheduler_stats(self) -> Dict[str, object]:
        """Live scheduler surface for ``/v1/stats`` and the router's
        load scoring: queue depths per SLO class, block-pool occupancy
        and headroom, prefix-cache effectiveness."""
        out = {
            "queue_depth": {c: len(q) for c, q in self._queues.items()},
            "queue_depth_total": sum(len(q)
                                     for q in self._queues.values()),
            "prefilling": len(self._prefilling),
            "free_blocks": self.pool.free_count,
            "block_capacity": self.pool.capacity,
            "block_occupancy": round(self.pool.occupancy(), 4),
            "prefix_hit_rate": round(self.stats.prefix_hit_rate, 4),
            "deferred_admissions": self.stats.deferred_blocks,
            "rejected_full": self.stats.rejected_full,
            "shed_deadline": self.stats.shed_deadline,
            "expired_deadline": self.stats.expired_deadline,
        }
        # Occupancy split (always present; draft is 0 on a target-only
        # engine) so capacity regressions are attributable to the pool
        # that grew — the router can weight draft pressure separately.
        cap = max(self.pool.capacity, 1)
        draft_used = self._draft_blocks_live
        out["draft_blocks_used"] = draft_used
        out["block_occupancy_draft"] = round(draft_used / cap, 4)
        out["block_occupancy_target"] = round(
            max(self.pool.used_count - draft_used, 0) / cap, 4)
        if self._draft_spec is not None:
            out["speculative"] = {
                "gamma": self._gamma,
                "gamma_max": self._gamma_max,
                "accept_ewma": round(self._accept_ewma, 4),
                "rounds": self.stats.spec_rounds,
                "proposed": self.stats.draft_tokens_proposed,
                "accepted": self.stats.draft_tokens_accepted,
                "bonus": self.stats.bonus_tokens,
                "acceptance_rate": round(self.stats.acceptance_rate, 4),
                "mean_accept_len": round(self.stats.mean_accept_len, 4),
                "gamma_hist": dict(self._gamma_hist),
            }
        if self.trie is not None:
            out["trie_blocks"] = len(self.trie)
            out["trie_evictions"] = self.trie.stats.evictions
        return out

    def assert_no_leaks(self) -> None:
        """Post-drain invariant (the bench gate): every pool block is
        either free or held exactly by the prefix cache."""
        assert not self._prefilling and not np.any(self._active), \
            "assert_no_leaks needs a drained engine"
        self.pool.verify()
        cached = len(self.trie.cached_blocks()) if self.trie else 0
        assert self.pool.used_count == cached, (
            f"{self.pool.used_count - cached} block(s) leaked "
            f"(used={self.pool.used_count}, trie-cached={cached})")
        assert self._draft_blocks_live == 0, (
            f"{self._draft_blocks_live} draft block(s) leaked")
        assert np.all(self._dbt == SCRATCH_BLOCK), \
            "draft block-table rows leaked (stale entries after drain)"

    # ------------------------------------------------------------------
    # scheduler internals
    # ------------------------------------------------------------------
    _REBASE_AT = 1 << 24

    def _rebase_tick(self) -> None:
        """Bound absolute-tick growth under sustained load, as in the
        slot engine: shift tick and per-slot bounds together (all
        device-visible position math is differences), zero inactive
        slots' dead bounds."""
        if self._tick < self._REBASE_AT:
            return
        shift = self._tick
        self._tick -= shift
        self._start -= shift
        self._p_end -= shift
        self._end -= shift
        inactive = ~self._active
        self._start[inactive] = 0
        self._p_end[inactive] = 0
        self._end[inactive] = 0

    def _free_slots(self) -> List[int]:
        return [b for b in range(self._slots)
                if not self._active[b] and b not in self._prefilling]

    def _admit(self) -> None:
        """Admit queued requests into free slots under the block
        budget, latency class strictly first.  A class whose head
        cannot allocate (even after trie eviction) blocks ITS class —
        strict FIFO per class, no size-based queue jumping — but a
        lower class may still admit into remaining slots."""
        free = self._free_slots()
        for slo in SLO_CLASSES:
            q = self._queues[slo]
            while q and free:
                if not self._try_allocate(q[0]):
                    self.stats.deferred_blocks += 1
                    break
                req = q.popleft()
                self._place(req, free.pop(0))

    def _try_allocate(self, req: PagedRequest) -> bool:
        """Reserve the request's whole worst-case span in blocks:
        trie-matched prefix blocks are referenced (not recomputed), the
        rest allocated fresh, with ``reserve_blocks`` kept free as the
        watermark.  On a speculative engine the DRAFT span is
        pre-reserved in the same breath — draft K/V is just more pages
        of the same pool, so the admission math is one sum: a
        speculative request needs ``blocks_for_tokens(span)`` twice
        (the draft processes at most ``span - 1`` prompt+committed
        positions, so the same block count covers it), and either both
        spans fit or neither is taken.  Draft blocks are always fresh
        (never trie-shared: their contents are the DRAFT model's K/V,
        incompatible with target prefix reuse).  All-or-nothing; under
        pressure unpinned cached blocks are LRU-evicted first."""
        span = req.prompt.size + req.max_new_tokens
        need_total = self.pool.blocks_for_tokens(span)
        need_draft = need_total if self._draft_spec is not None else 0
        n_cached, cached = (self.trie.match(req.prompt)
                            if self.trie is not None else (0, []))
        need_new = need_total - len(cached)
        short = need_new + need_draft + self._reserve \
            - self.pool.free_count
        if short > 0 and self.trie is not None:
            self.trie.evict(short)
        if self.pool.free_count < need_new + need_draft + self._reserve:
            for blk in cached:      # undo the match references
                self.pool.release(blk)
            return False
        try:
            both = self.pool.alloc(need_new + need_draft)
        except BlockPoolExhausted:   # pragma: no cover - guarded above
            for blk in cached:
                self.pool.release(blk)
            return False
        fresh, draft = both[:need_new], both[need_new:]
        req.blocks = cached + fresh
        req.draft_blocks = draft
        self._draft_blocks_live += len(draft)
        req.n_cached = n_cached
        req.charged = n_cached
        req.draft_charged = 0       # no trie for draft pages
        return True

    def _place(self, req: PagedRequest, b: int) -> None:
        """Bind an allocated request to a slot: block table row, prompt
        tokens to the device row, per-slot sampling knobs; prefill runs
        at the next dispatch wave."""
        p = req.prompt.size
        self._bt[b, :] = SCRATCH_BLOCK
        self._bt[b, :len(req.blocks)] = req.blocks
        self._dbt[b, :] = SCRATCH_BLOCK
        self._dbt[b, :len(req.draft_blocks)] = req.draft_blocks
        self._committed[b] = 0
        pb = _pow2_bucket(p, self._window)
        padded = np.zeros(pb, np.int32)
        padded[:p] = req.prompt
        try:
            self._tokens = _write_prompt_program(
                self._tokens, jnp.asarray(padded), np.int32(b),
                np.int32(0))
        except Exception:
            self._poisoned = True
            raise
        self._temp[b] = req.temperature
        self._eos[b] = req.eos_id
        req.slot = b
        req.admit_t = time.monotonic()
        self._prefilling[b] = req
        self._active[b] = False
        self._done[b] = True
        self.stats.prompt_tokens += p
        self.stats.cached_prompt_tokens += req.n_cached
        if req.n_cached:
            self.stats.prefix_requests += 1

    def _next_chunk_len(self, req: PagedRequest) -> int:
        remaining = req.prompt.size - req.charged
        if self._prefill_chunk is None:
            return remaining
        return min(self._prefill_chunk, remaining)

    def _dispatch_prefills(self) -> None:
        """One prefill wave: each prefilling request charges its next
        chunk, batched by pow-2 chunk bucket into few dispatches (the
        compile dimensions are the bucket and the pow-2-padded row
        count, both logarithmic sets).  On a speculative engine the
        DRAFT model then catches up to the target's charge level over
        its own pages in a second bucketed pass — the draft has no
        prefix cache, so its first chunk also covers the trie-matched
        region the target skipped."""
        wave = [self._prefilling[b] for b in sorted(self._prefilling)]
        buckets: Dict[int, List[PagedRequest]] = {}
        for req in wave:
            c = self._next_chunk_len(req)
            pb = _pow2_bucket(c, self._window)
            buckets.setdefault(pb, []).append(req)
        for pb in sorted(buckets):
            entries = buckets[pb]
            while entries:
                k = 1 << (len(entries).bit_length() - 1)   # pow2 <= len
                self._run_prefill_chunk(entries[:k], pb)
                entries = entries[k:]
        if self._draft_spec is None:
            return
        # Draft catch-up (requests that just finished their FINAL
        # target chunk left _prefilling, but still need draft pages
        # charged before their first spec round — hence the wave
        # snapshot above).
        dbuckets: Dict[int, List[PagedRequest]] = {}
        for req in wave:
            c = req.charged - req.draft_charged
            if c > 0:
                dbuckets.setdefault(_pow2_bucket(c, self._window),
                                    []).append(req)
        for pb in sorted(dbuckets):
            entries = dbuckets[pb]
            while entries:
                k = 1 << (len(entries).bit_length() - 1)
                self._run_draft_prefill_chunk(entries[:k], pb)
                entries = entries[k:]

    def _run_prefill_chunk(self, reqs: List[PagedRequest],
                           pb: int) -> None:
        k_real = len(reqs)
        k_pad = 1 << (k_real - 1).bit_length()
        chunk = np.zeros((k_pad, pb), np.int32)
        n_shared = np.zeros(k_pad, np.int32)
        c_lens = np.ones(k_pad, np.int32)
        is_final = np.zeros(k_pad, bool)
        slot_ids = np.zeros(k_pad, np.int32)
        bt_rows = np.full((k_pad, self._maxb), SCRATCH_BLOCK, np.int32)
        for i in range(k_pad):
            req = reqs[min(i, k_real - 1)]   # pad repeats the last row
            c = self._next_chunk_len(req)
            chunk[i, :c] = req.prompt[req.charged:req.charged + c]
            n_shared[i] = req.charged
            c_lens[i] = c
            is_final[i] = req.charged + c == req.prompt.size
            slot_ids[i] = req.slot
            bt_rows[i] = self._bt[req.slot]
        self._rng, sub = jax.random.split(self._rng)
        try:
            self._tokens, self._kc, self._vc, landed, _ = \
                _paged_prefill_program(
                    self._knobs, self._params, self._tokens, self._kc,
                    self._vc, jnp.asarray(chunk), jnp.asarray(bt_rows),
                    jnp.asarray(slot_ids), jnp.asarray(n_shared),
                    jnp.asarray(c_lens), jnp.asarray(is_final),
                    jnp.asarray(self._temp), sub)
            landed = np.array(landed)
        except Exception:
            self._poisoned = True
            raise
        self.stats.prefill_dispatches += 1
        now = time.monotonic()
        for i, req in enumerate(reqs):
            c = int(c_lens[i])
            req.charged += c
            self.stats.prefill_chunks += 1
            if not is_final[i]:
                continue
            # Final chunk: the request joins the decode batch at the
            # CURRENT tick with its whole prompt behind it.
            b, p = req.slot, req.prompt.size
            t0 = self._tick
            self._start[b] = t0 - p
            self._p_end[b] = t0
            self._end[b] = t0 + req.max_new_tokens
            tok = int(landed[i])
            self._done[b] = (req.max_new_tokens == 1
                             or (req.eos_id >= 0 and tok == req.eos_id))
            self._active[b] = True
            self._committed[b] = p + 1   # prompt + the landed token
            self._slot_req[b] = req
            del self._prefilling[b]
            req.first_token_t = now
            if self.trie is not None:
                self.trie.insert(req.prompt, req.blocks)

    def _run_draft_prefill_chunk(self, reqs: List[PagedRequest],
                                 pb: int) -> None:
        """Charge a prompt chunk into the DRAFT model's pages: the same
        ``_paged_prefill_program`` (same traced shape family) over the
        draft params/pools and the draft block table.  The draft has no
        prefix cache — ``n_shared`` is the request's own draft charge,
        so its first chunk recomputes the trie-matched region the
        target skipped (draft K/V is model-specific; target cache
        entries cannot seed it).  Never ``is_final``: only the TARGET
        ever samples tokens."""
        k_real = len(reqs)
        k_pad = 1 << (k_real - 1).bit_length()
        chunk = np.zeros((k_pad, pb), np.int32)
        n_shared = np.zeros(k_pad, np.int32)
        c_lens = np.ones(k_pad, np.int32)
        is_final = np.zeros(k_pad, bool)
        slot_ids = np.zeros(k_pad, np.int32)
        bt_rows = np.full((k_pad, self._maxb), SCRATCH_BLOCK, np.int32)
        for i in range(k_pad):
            req = reqs[min(i, k_real - 1)]   # pad repeats the last row
            c = req.charged - req.draft_charged
            chunk[i, :c] = req.prompt[req.draft_charged:req.charged]
            n_shared[i] = req.draft_charged
            c_lens[i] = c
            slot_ids[i] = req.slot
            bt_rows[i] = self._dbt[req.slot]
        self._rng, sub = jax.random.split(self._rng)
        try:
            self._tokens, self._dkc, self._dvc, _, _ = \
                _paged_prefill_program(
                    self._knobs, self._draft_params, self._tokens,
                    self._dkc, self._dvc, jnp.asarray(chunk),
                    jnp.asarray(bt_rows), jnp.asarray(slot_ids),
                    jnp.asarray(n_shared), jnp.asarray(c_lens),
                    jnp.asarray(is_final), jnp.asarray(self._temp), sub)
        except Exception:
            self._poisoned = True
            raise
        self.stats.draft_prefill_dispatches += 1
        for req in reqs:
            req.draft_charged = req.charged

    def _run_chunk(self) -> None:
        n = self._chunk
        if any(self._queues.values()) or self._prefilling:
            # Work is waiting: clamp to the next KNOWN retirement
            # (pow-2-quantized down, as in the slot engine) so freed
            # slots refill immediately.
            live = self._active & ~self._done
            if live.any():
                nxt = int(self._end[live].min()) - 1 - self._tick
                if 0 < nxt < n:
                    n = 1 << (nxt.bit_length() - 1)
        self._rng, sub = jax.random.split(self._rng)
        try:
            self._tokens, self._kc, self._vc, done, busy = \
                _paged_chunk_program(
                    n, self._knobs, self._params, self._tokens,
                    self._kc, self._vc, jnp.asarray(self._bt),
                    jnp.asarray(self._start), jnp.asarray(self._p_end),
                    jnp.asarray(self._end), jnp.asarray(self._done),
                    jnp.asarray(self._active),
                    jnp.asarray(self._temp), jnp.asarray(self._eos),
                    jnp.int32(self._tick), sub)
            self._done = np.array(done)
        except Exception:
            self._poisoned = True
            raise
        self._tick += n
        self.stats.ticks += n
        self.stats.busy_slot_ticks += int(busy)
        self.stats.chunks += 1

    def _retune_gamma(self) -> None:
        """SLO-aware gamma adaptation, one adjustment per round:

        * **shrink** toward 1 when the latency class queues back up or
          every slot is taken with work still waiting — speculation
          spends batch-wide verify FLOPs to cut per-request latency,
          exactly the wrong trade when requests are queueing;
        * **grow** back toward ``gamma_max`` when slots idle and
          nothing is queued (the utilization gap speculation exists to
          spend);
        * an acceptance-length EWMA caps gamma at ``2 * ewma`` so a
          badly-mismatched draft degrades toward plain decode (gamma 1)
          instead of paying gamma-deep drafts it never lands.
        """
        if not self._adapt_gamma:
            return
        g = self._gamma
        queued = any(self._queues.values())
        free = len(self._free_slots())
        if self._queues[SLO_LATENCY] or (free == 0 and queued):
            g = max(1, g - 1)
        elif free > 0 and not queued:
            g = min(self._gamma_max, g + 1)
        self._gamma = min(g, max(1, int(round(2 * self._accept_ewma))))

    def _run_spec_round(self) -> None:
        """One draft-and-verify round over every live decode slot — the
        speculative replacement for ``_run_chunk``'s per-token ticks.

        Let ``m`` be a slot's committed token count (prompt + landed;
        its target K/V covers positions ``0..m-2``, its tokens row is
        authoritative through ``m-1``) and ``ge = min(gamma, request
        cap, tokens remaining)``.  The round is two dispatches plus one
        point-write:

        1. **Draft scan** — ``_paged_chunk_program`` over the draft
           params/pools/table, re-based so tick 0 is a CATCH-UP tick:
           ``start = 2 - m`` makes ``rel`` walk ``m-2, m-1, ...``, and
           ``p_end = 2`` keeps tick 0 teacher-forced, so it re-writes
           the draft K/V at ``m-2`` (covering the committed tokens a
           full acceptance landed past the previous scan) WITHOUT
           touching the committed token at ``m-1``.  Ticks 1..ge then
           write greedy proposals at positions ``m..m+ge-1`` in the
           shared device tokens row (``temp=0``, ``eos=-1``: proposal
           depth is bounded by ``end = ge + 2``, never by content).
        2. **Verify** — ``_paged_prefill_program`` over the TARGET with
           the committed token + proposals as a ``ge+1``-token chunk at
           ``n_shared = m-1``: one dispatch scores all candidates and
           returns ``preds`` (the target argmax at every position).
           The chunk is GATHERED ON DEVICE from the tokens buffer the
           draft just wrote — draft and verify queue back-to-back on
           the device stream, and the round pays exactly ONE host sync
           (fetching ``preds`` + proposals together after verify).
           Host-side greedy acceptance takes the longest agreeing
           prefix ``a`` and the target's own token at the first
           mismatch as the bonus — so every round commits ``a+1``
           tokens (capped at the request's budget) and the output is
           token-exact vs the target-only oracle by construction.
        3. **Commit** — accepted proposals already sit in the tokens
           row (the draft wrote them); only the bonus needs a batched
           point-write (``_commit_tokens_program``).

        Stale-K/V safety is positional: the verify chunk's context mask
        stops at ``m-1`` and its own positions are freshly written, and
        the draft scan rewrites every position past ``m-2`` before any
        later tick attends it — rejected-proposal K/V from earlier
        rounds is always re-written before it is ever re-read."""
        live = [b for b in range(self._slots)
                if self._active[b] and not self._done[b]]
        if not live:
            return
        self._retune_gamma()
        g_used = self._gamma
        reqs = [self._slot_req[b] for b in live]
        m = np.array([int(self._committed[b]) for b in live])
        end_total = np.array([r.prompt.size + r.max_new_tokens
                              for r in reqs])
        ge = np.minimum(np.minimum(g_used,
                                   np.array([r.gamma for r in reqs])),
                        end_total - m).astype(np.int32)
        # --- draft scan ------------------------------------------------
        start = np.zeros(self._slots, np.int32)
        p_end = np.zeros(self._slots, np.int32)
        end = np.zeros(self._slots, np.int32)
        done0 = np.ones(self._slots, bool)
        active = np.zeros(self._slots, bool)
        for i, b in enumerate(live):
            start[b] = 2 - m[i]
            p_end[b] = 2
            end[b] = int(ge[i]) + 2
            done0[b] = False
            active[b] = True
        # Exact tick count, not a pow-2 bucket: the static set is
        # {2..gamma_max+1} — as bounded as a bucket family, without the
        # dead padding ticks a pow-2 round-up would add to every round.
        n = int(ge.max()) + 1
        dtemp = np.zeros(self._slots, np.float32)    # greedy proposals
        deos = np.full(self._slots, -1, np.int32)    # depth-bounded only
        self._rng, sub = jax.random.split(self._rng)
        t0 = time.monotonic()
        try:
            self._tokens, self._dkc, self._dvc, _, _ = \
                _paged_chunk_program(
                    n, self._knobs, self._draft_params, self._tokens,
                    self._dkc, self._dvc, jnp.asarray(self._dbt),
                    jnp.asarray(start), jnp.asarray(p_end),
                    jnp.asarray(end), jnp.asarray(done0),
                    jnp.asarray(active), jnp.asarray(dtemp),
                    jnp.asarray(deos), jnp.int32(0), sub)
        except Exception:
            self._poisoned = True
            raise
        t1 = time.monotonic()
        # --- verify ----------------------------------------------------
        k_real = len(live)
        k_pad = 1 << (k_real - 1).bit_length()
        pb = _pow2_bucket(int(ge.max()) + 1, self._window)
        n_shared = np.zeros(k_pad, np.int32)
        c_lens = np.ones(k_pad, np.int32)
        is_final = np.zeros(k_pad, bool)
        slot_ids = np.zeros(k_pad, np.int32)
        bt_rows = np.full((k_pad, self._maxb), SCRATCH_BLOCK, np.int32)
        cols = np.zeros((k_pad, pb), np.int32)
        for i in range(k_pad):
            j = min(i, k_real - 1)       # pad repeats the last row
            b = live[j]
            n_shared[i] = m[j] - 1
            c_lens[i] = int(ge[j]) + 1
            slot_ids[i] = b
            bt_rows[i] = self._bt[b]
            cols[i] = np.clip(m[j] - 1 + np.arange(pb), 0,
                              self._window - 1)
        self._rng, sub = jax.random.split(self._rng)
        try:
            # Device-side gather: the committed token + proposals are
            # already rows of the tokens buffer the draft scan wrote.
            chunk = self._tokens[jnp.asarray(slot_ids)[:, None],
                                 jnp.asarray(cols)]
            self._tokens, self._kc, self._vc, _, preds = \
                _paged_prefill_program(
                    self._knobs, self._params, self._tokens, self._kc,
                    self._vc, chunk, jnp.asarray(bt_rows),
                    jnp.asarray(slot_ids), jnp.asarray(n_shared),
                    jnp.asarray(c_lens), jnp.asarray(is_final),
                    jnp.asarray(self._temp), sub)
            preds = np.asarray(preds)    # the round's ONE host sync
            toks = np.asarray(self._tokens)
        except Exception:
            self._poisoned = True
            raise
        t2 = time.monotonic()
        # --- host acceptance + bonus commit ----------------------------
        rows: List[int] = []
        pos: List[int] = []
        vals: List[int] = []
        accepts = []
        for i, b in enumerate(live):
            req = reqs[i]
            g_i = int(ge[i])
            props = toks[b, m[i]:m[i] + g_i]
            a = 0
            while a < g_i and int(preds[i, a]) == int(props[a]):
                a += 1
            accepts.append(a)
            new_m = min(int(m[i]) + a + 1, int(end_total[i]))
            committed_new = [int(t) for t in props[:a]]
            if m[i] + a < end_total[i]:
                bonus = int(preds[i, a])
                rows.append(b)
                pos.append(int(m[i]) + a)
                vals.append(bonus)
                committed_new.append(bonus)
                req.spec_bonus += 1
                self.stats.bonus_tokens += 1
            req.spec_rounds += 1
            req.spec_proposed += g_i
            req.spec_accepted += a
            req.draft_s += t1 - t0
            req.verify_s += t2 - t1
            self.stats.draft_tokens_proposed += g_i
            self.stats.draft_tokens_accepted += a
            if new_m >= end_total[i] or (
                    req.eos_id >= 0 and req.eos_id in committed_new):
                self._done[b] = True
            self._committed[b] = new_m
        if rows:
            kp = 1 << (len(rows) - 1).bit_length()
            while len(rows) < kp:        # idempotent pow-2 padding
                rows.append(rows[-1])
                pos.append(pos[-1])
                vals.append(vals[-1])
            try:
                self._tokens = _commit_tokens_program(
                    self._tokens, jnp.asarray(np.array(rows, np.int32)),
                    jnp.asarray(np.array(pos, np.int32)),
                    jnp.asarray(np.array(vals, np.int32)))
            except Exception:
                self._poisoned = True
                raise
        self._accept_ewma = (0.8 * self._accept_ewma
                             + 0.2 * float(np.mean(accepts)))
        self._gamma_hist[g_used] = self._gamma_hist.get(g_used, 0) + 1
        self.stats.spec_rounds += len(live)
        self.stats.ticks += 1
        self.stats.busy_slot_ticks += len(live)

    def _slot_tokens(self, b: int, req: PagedRequest) -> np.ndarray:
        """Tokens written so far for slot ``b``: logical positions
        0..written-1 pulled as one row slice, eos-truncated after the
        prompt, prefix strip applied."""
        s, pe, e = int(self._start[b]), int(self._p_end[b]), \
            int(self._end[b])
        if self._draft_spec is not None:
            # Spec rounds advance by a variable amount; the per-slot
            # committed count is the progress measure, not the tick.
            written = int(self._committed[b])
        else:
            written = min(e, self._tick + 1) - s
        row = np.array(self._tokens[b])
        seq = row[:max(written, 0)]
        eos = int(self._eos[b])
        p = pe - s
        if eos >= 0:
            gen = seq[p:]
            hits = np.nonzero(gen == eos)[0]
            if hits.size:
                seq = seq[:p + hits[0] + 1]
        return seq[req.strip:]

    def _emit_request_spans(self, req: PagedRequest, gen: int) -> None:
        """Record the request's lifecycle spans (queue-wait, chunked
        prefill, decode) into the telemetry span stream at harvest —
        the request is terminal here, so every boundary timestamp is
        known and the emission rides a path that already paid a host
        sync.  Monotonic times anchor to wall clock at 'now'; never
        raises (record_span's contract)."""
        from autodist_tpu.telemetry.profiler import record_span

        now_mono = req.done_t or time.monotonic()
        now_wall = time.time()

        def wall(mono: float) -> float:
            return now_wall - (now_mono - mono)

        admit = req.admit_t or now_mono
        record_span("queue_wait", start_unix=wall(req.submit_t),
                    dur_s=max(admit - req.submit_t, 0.0),
                    trace_id=req.trace_id,
                    request_id=req.request_id, slo=req.slo)
        first = req.first_token_t or admit
        record_span("prefill", start_unix=wall(admit),
                    dur_s=max(first - admit, 0.0),
                    trace_id=req.trace_id, request_id=req.request_id,
                    prompt_tokens=int(req.prompt.size),
                    cached_tokens=int(req.n_cached))
        record_span("decode", start_unix=wall(first),
                    dur_s=max(now_mono - first, 0.0),
                    trace_id=req.trace_id, request_id=req.request_id,
                    generated=int(gen))
        if req.spec_rounds:
            # Cumulative draft/verify windows inside the decode span,
            # so the trace export shows where speculative rounds spent
            # their time (draft proposing vs target verifying).
            record_span("spec_draft", start_unix=wall(first),
                        dur_s=req.draft_s, trace_id=req.trace_id,
                        request_id=req.request_id,
                        rounds=int(req.spec_rounds),
                        proposed=int(req.spec_proposed),
                        accepted=int(req.spec_accepted))
            record_span("spec_verify", start_unix=wall(first),
                        dur_s=req.verify_s, trace_id=req.trace_id,
                        request_id=req.request_id,
                        bonus=int(req.spec_bonus))

    def _free_slot(self, b: int, req: PagedRequest) -> None:
        """Return the request's blocks to the pool (shared prefix
        blocks just drop this reader's reference; draft pages are
        request-private, so they always free) and clear both block
        table rows — the slot and the memory recycle at THIS
        boundary."""
        for blk in req.blocks:
            self.pool.release(blk)
        req.blocks = []
        for blk in req.draft_blocks:
            self.pool.release(blk)
        self._draft_blocks_live -= len(req.draft_blocks)
        req.draft_blocks = []
        self._bt[b, :] = SCRATCH_BLOCK
        self._dbt[b, :] = SCRATCH_BLOCK

    def _harvest(self) -> None:
        for b in range(self._slots):
            if not (self._active[b] and self._done[b]):
                continue
            req = self._slot_req[b]
            seq = self._slot_tokens(b, req)
            gen = max(seq.size - (req.prompt.size - req.strip), 0)
            self.stats.generated_tokens += gen
            self.stats.completed += 1
            self._results[req.request_id] = seq
            self._active[b] = False
            self._slot_req[b] = None
            self._free_slot(b, req)
            req.done_t = time.monotonic()
            wall = req.done_t - req.submit_t
            self._avg_request_s = (0.8 * self._avg_request_s + 0.2 * wall
                                   if self._avg_request_s else wall)
            ttft = ((req.first_token_t - req.submit_t)
                    if req.first_token_t else wall)
            per_tok = ((req.done_t - req.first_token_t) / max(gen - 1, 1)
                       if req.first_token_t and gen > 1 else 0.0)
            # Service-rate samples for the deadline-shed estimate.
            self._qwait_samples.append(
                (req.admit_t or req.done_t) - req.submit_t)
            if per_tok > 0.0:
                self._per_tok_samples.append(per_tok)
            self._emit_request_spans(req, gen)
            self._timings[req.request_id] = {
                "queue_wait_s": (req.admit_t or req.done_t) - req.submit_t,
                "ttft_s": ttft,
                "per_token_s": per_tok,
                "generated": float(gen),
                "cached_tokens": float(req.n_cached),
                "trace_id": req.trace_id,
                "slo": req.slo,
            }
            if self._draft_spec is not None:
                self._timings[req.request_id].update({
                    "spec_rounds": float(req.spec_rounds),
                    "spec_proposed": float(req.spec_proposed),
                    "spec_accepted": float(req.spec_accepted),
                    "spec_bonus": float(req.spec_bonus),
                    "accept_len_mean": (
                        req.spec_accepted / req.spec_rounds
                        if req.spec_rounds else 0.0),
                    "draft_s": req.draft_s,
                    "verify_s": req.verify_s,
                })
