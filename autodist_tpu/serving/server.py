"""HTTP serving front for :class:`~autodist_tpu.serving.engine.DecodeEngine`.

The engine is a host-side continuous-batching scheduler; this module puts a
network boundary in front of it so the framework's serving story runs end to
end: model → engine → deployable server.  Stdlib only (``http.server`` +
``threading``) — no web-framework dependency to gate on.

The reference has no serving subsystem at all (its execution layer stops at
``WrappedSession.run``, ``autodist/runner.py:78-132``); this is beyond-parity
scope layered on the engine.

Design: ONE driver thread owns the decode loop (``engine.step()`` under the
server lock — the engine is not thread-safe), handler threads submit/cancel/
stream under the same lock (released and handed over between chunks) and
block on a per-request Event until their request id is harvested.  Sampling
knobs are engine-wide trace-time constants (see
``DecodeEngine``), so the per-request surface is ``prompt`` ×
``max_new_tokens`` × ``stream``.

Endpoints
---------
- ``POST /v1/completions`` — body ``{"prompt_tokens": [ints],
  "max_new_tokens": N, "stream": false}``; with a tokenizer installed,
  ``"prompt": "text"`` is accepted and ``"text"`` is returned.  Streaming
  responses are Server-Sent Events, one ``data:`` JSON per new-token delta.
- ``POST /v1/cancel`` — body ``{"id": N}``.
- ``POST /admin/drain`` — stop admitting (429 + ``"draining": true``),
  finish in-flight work; ``POST /admin/undrain`` reverses it.  SIGTERM
  triggers the same drain when :func:`install_drain_on_sigterm` is
  installed (``serve()`` does, best-effort), then exits
  ``DRAINED_EXIT_CODE`` once idle — the supervisor's budget-free
  preemption relaunch path, which is what makes
  ``SupervisedReplicaPool.rolling_restart()`` drop nothing.
- ``GET /v1/stats`` — engine counters + server counters (+ request
  latency p50/p99 estimated from the latency histogram) + the
  ``draining`` flag the router's candidate filter reads.
- ``GET /metrics`` — Prometheus text exposition
  (``autodist_serving_*``: request latency + queue-depth histograms,
  served/failed counters, outstanding gauge — docs/observability.md).
- ``GET /healthz``.
"""
from __future__ import annotations

import json
import os
import signal
import threading
import time
import uuid
from dataclasses import asdict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

import numpy as np

from autodist_tpu.resilience.chaos import ServingChaos
from autodist_tpu.serving.engine import (AdmissionError, DeadlineError,
                                         DecodeEngine)
from autodist_tpu.telemetry.registry import (
    DEPTH_BUCKETS,
    MetricsRegistry,
    TIME_BUCKETS,
    render_prometheus,
)
from autodist_tpu.utils import logging

_MAX_BODY_BYTES = 8 << 20
_CANCELLED = object()   # sentinel in the done-map for cancelled requests
_DEADLINE = object()    # ... and for deadline-expired requests (504)


class EngineServer:
    """Serve a :class:`DecodeEngine` over HTTP.

    ``tokenizer`` (optional) is any object with ``encode(str) -> list[int]``
    and ``decode(seq[int]) -> str``; installing one enables the ``"prompt"``
    string form and ``"text"`` in responses.

    ``request_timeout_s`` bounds how long a completion request may wait
    end-to-end before the handler answers 504 and cancels the request
    (freeing its slot).
    """

    def __init__(self, engine: DecodeEngine, *, host: str = "127.0.0.1",
                 port: int = 0, tokenizer=None,
                 request_timeout_s: float = 600.0):
        self._engine = engine
        if getattr(engine, "_replicate", None) is not None:
            # A multi-PROCESS engine requires every process to drive the
            # scheduler in SPMD lockstep (identical submissions -> its
            # host pulls are cross-process collectives).  HTTP requests
            # land on ONE process, so serving it here would hang the
            # other processes in the first collective — fail at
            # construction instead.  Multi-process serving is driven by
            # a lockstep harness (tests/integration/dist_train.py).
            raise ValueError(
                "EngineServer cannot drive a multi-process DecodeEngine: "
                "HTTP requests arrive on one process while the engine's "
                "host pulls are cross-process collectives requiring SPMD "
                "lockstep; run the server on a single-process mesh, or "
                "drive the multi-process engine from a lockstep script")
        self._tokenizer = tokenizer
        tok_vocab = getattr(tokenizer, "vocab_size", None)
        if tok_vocab is not None and tok_vocab < engine._vocab:
            # Fail at construction, not mid-response: the model can
            # sample ids the tokenizer cannot decode.
            raise ValueError(
                f"tokenizer vocab_size {tok_vocab} < model vocab "
                f"{engine._vocab}: generated ids would not decode")
        self._timeout = float(request_timeout_s)
        # DRAINING: stop admitting (429 + "draining": true), finish
        # in-flight work.  Set by POST /admin/drain or SIGTERM (see
        # install_drain_on_sigterm); the router's candidate filter
        # reads the flag off /v1/stats and skips the replica.
        self._draining = False
        self._n_submitted = 0
        self._deadline_info: Dict[int, Dict[str, Any]] = {}
        # Serving-plane chaos (AUTODIST_CHAOS kill_replica/slow_replica/
        # drop_response/stale_stats), clocked by the driver loop on
        # serving progress; empty spec = no-op.
        self._chaos = ServingChaos.from_env()
        self._stale_stats: Optional[Dict[str, Any]] = None

        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)        # new submits
        # Handlers wanting the lock bump this; the driver yields to them
        # between iterations.  Python locks are NOT fair — the driver
        # releasing and immediately re-acquiring would otherwise starve
        # handler threads for the whole drain (a submit could not join a
        # running batch).  The counter has its own tiny lock: '+=' is
        # not atomic, and a lost update would drift the count
        # permanently.  The driver READS it unlocked — a stale read is
        # transient and harmless.
        self._meta_lock = threading.Lock()   # waiter count + counters
        self._handler_waiters = 0
        self._outstanding: set = set()
        self._done: Dict[int, Any] = {}          # rid -> tokens | _CANCELLED
        # Completion signalling is per-request Events, NOT a shared
        # condition: a condition waiter re-acquires the unfair lock on
        # notify and can starve behind the driver; Event.wait holds no
        # lock at all.
        self._events: Dict[int, threading.Event] = {}
        self._engine_error: Optional[BaseException] = None
        self._stop = False
        self.requests_served = 0
        self.requests_failed = 0
        # Telemetry (docs/observability.md): an EXPLICIT registry — the
        # /metrics endpoint is a server feature, live regardless of the
        # AUTODIST_TELEMETRY instrumentation switch.  Fixed-bound
        # histograms so a multi-replica deployment's scrapes merge
        # exactly.
        self._registry = MetricsRegistry()
        self._m_latency = self._registry.histogram(
            "autodist_serving_request_latency_seconds",
            "end-to-end completion latency (submit to final token)",
            buckets=TIME_BUCKETS)
        self._m_queue = self._registry.histogram(
            "autodist_serving_queue_depth",
            "requests outstanding at submit time",
            buckets=DEPTH_BUCKETS)
        self._m_served = self._registry.counter(
            "autodist_serving_requests_served_total",
            "completion requests answered successfully")
        self._m_failed = self._registry.counter(
            "autodist_serving_requests_failed_total",
            "completion requests failed/cancelled/timed out")
        self._m_outstanding = self._registry.gauge(
            "autodist_serving_outstanding", "requests currently in flight")
        # Fault-tolerance surface (docs/serving.md "Fault tolerance").
        self._m_shed = self._registry.counter(
            "autodist_serving_shed_total",
            "requests shed at admission: measured service rates say "
            "the deadline cannot be met (503)")
        self._m_expired = self._registry.counter(
            "autodist_serving_deadline_expired_total",
            "admitted requests cancelled past their deadline (504)")
        self._m_drain_refused = self._registry.counter(
            "autodist_serving_drain_refused_total",
            "requests refused because the replica is draining (429)")
        self._m_timeouts = self._registry.counter(
            "autodist_serving_timeouts_total",
            "requests that hit request_timeout_s and were cancelled "
            "(504)")
        self._m_draining = self._registry.gauge(
            "autodist_serving_draining",
            "1 while the replica is draining, else 0")
        # Scheduler-backed engines (PagedDecodeEngine) report richer
        # latency + occupancy telemetry: time-to-first-token and
        # inter-token latency histograms (fixed bounds — multi-replica
        # scrapes merge exactly) fed from the engine's per-request
        # timings, plus live block-pool / queue-depth gauges refreshed
        # by the driver loop.
        self._paged = hasattr(engine, "scheduler_stats")
        if self._paged:
            self._m_ttft = self._registry.histogram(
                "autodist_serving_ttft_seconds",
                "submit to first generated token", buckets=TIME_BUCKETS)
            self._m_itl = self._registry.histogram(
                "autodist_serving_per_token_seconds",
                "mean inter-token latency after the first token",
                buckets=TIME_BUCKETS)
            self._m_queue_wait = self._registry.histogram(
                "autodist_serving_queue_wait_seconds",
                "submit to admission (slot + blocks assigned)",
                buckets=TIME_BUCKETS)
            self._m_occupancy = self._registry.gauge(
                "autodist_serving_block_occupancy",
                "fraction of the paged KV block pool in use")
            self._m_prefix_rate = self._registry.gauge(
                "autodist_serving_prefix_hit_rate",
                "fraction of prompt tokens served from the prefix cache")
            self._m_class_depth = {
                c: self._registry.gauge(
                    "autodist_serving_queue_depth_class",
                    "admission queue depth by SLO class",
                    labels={"slo": c})
                for c in ("latency", "throughput")}
            self._m_occ_target = self._registry.gauge(
                "autodist_serving_block_occupancy_target",
                "pool fraction holding TARGET-model KV blocks")
            self._m_occ_draft = self._registry.gauge(
                "autodist_serving_block_occupancy_draft",
                "pool fraction holding draft-model KV blocks "
                "(speculative decoding)")
        # Speculative-mode telemetry (engine built with a draft model):
        # fixed-bound histograms again, so the acceptance-length and
        # gamma distributions merge exactly across replicas.
        self._spec = getattr(engine, "_draft_spec", None) is not None
        if self._spec:
            self._m_accept_len = self._registry.histogram(
                "autodist_serving_spec_accept_len",
                "mean accepted draft tokens per verify round, per "
                "request", buckets=DEPTH_BUCKETS)
            self._m_gamma_hist = self._registry.histogram(
                "autodist_serving_spec_gamma",
                "SLO-adapted proposal depth, sampled per driver fold",
                buckets=DEPTH_BUCKETS)
            self._m_gamma = self._registry.gauge(
                "autodist_serving_spec_gamma_current",
                "current SLO-adapted proposal depth")

        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.owner = self
        self._driver = threading.Thread(target=self._drive,
                                        name="engine-server-driver",
                                        daemon=True)
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, name="engine-server-http",
            daemon=True)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "EngineServer":
        self._driver.start()
        self._http_thread.start()
        logging.info("EngineServer listening on %s:%d", *self.address)
        return self

    def close(self) -> None:
        """Stop serving.  In-flight handler threads are woken and answer
        503; the engine object stays usable by the caller."""
        with self._lock:
            self._stop = True
            self._work.notify_all()
            for ev in self._events.values():
                ev.set()
            self._events.clear()
        self._httpd.shutdown()
        self._httpd.server_close()
        self._driver.join(timeout=10)

    def __enter__(self) -> "EngineServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    @property
    def address(self):
        return self._httpd.server_address[:2]

    # -- driver loop -------------------------------------------------------

    def _drive(self) -> None:
        # The lock is RELEASED between iterations: a handler thread must
        # be able to submit into (or stream from) the RUNNING batch —
        # holding the lock across the whole busy loop would serialize
        # the server into one batch per drain, defeating continuous
        # batching across concurrent HTTP requests.
        while True:
            if self._chaos:
                # Serving-chaos clock: fire on progress (submissions /
                # generated tokens), journal-before-execute.  Outside
                # the lock — an injected slow_replica sleep must not
                # also block handler submits.
                self._chaos.on_tick(
                    requests=self._n_submitted,
                    generated=int(getattr(self._engine.stats,
                                          "generated_tokens", 0)))
                if self._chaos.slow_s > 0:
                    time.sleep(self._chaos.slow_s)
            with self._lock:
                if self._stop:
                    return
                if not self._outstanding:
                    self._work.wait(timeout=0.25)
                    continue
                try:
                    self._engine.step()
                except Exception as e:   # poisoned engine, device loss
                    self._engine_error = e
                    logging.error("EngineServer: engine failed: %r", e)
                for rid, toks in self._engine.results().items():
                    if rid in self._outstanding:
                        self._outstanding.discard(rid)
                        self._done[rid] = toks
                        ev = self._events.pop(rid, None)
                        if ev is not None:
                            ev.set()
                if self._paged:
                    # Deadline sweep results: resolve the waiters of
                    # requests the scheduler cancelled past-deadline
                    # (504 + Retry-After) instead of letting them ride
                    # to the request timeout.
                    for rid, info in self._engine.pop_expired().items():
                        if rid in self._outstanding:
                            self._outstanding.discard(rid)
                            self._done[rid] = _DEADLINE
                            self._deadline_info[rid] = info
                            self._m_expired.inc()
                            ev = self._events.pop(rid, None)
                            if ev is not None:
                                ev.set()
                    self._observe_paged()
                if self._engine_error is not None:
                    # In-flight work is lost (donated buffers); fail the
                    # waiters loudly rather than hang them to timeout.
                    self._outstanding.clear()
                    for ev in self._events.values():
                        ev.set()
                    self._events.clear()
                    return
            if self._handler_waiters:
                time.sleep(0.001)   # hand the lock to a waiting handler

    def _observe_paged(self) -> None:
        """Fold the scheduler's per-request timings and live occupancy
        into the server registry (driver thread, under the lock)."""
        for timing in self._engine.pop_timings().values():
            self._m_ttft.observe(timing["ttft_s"])
            self._m_queue_wait.observe(timing["queue_wait_s"])
            if timing.get("per_token_s"):
                self._m_itl.observe(timing["per_token_s"])
            if self._spec and timing.get("spec_rounds"):
                self._m_accept_len.observe(timing["accept_len_mean"])
        sched = self._engine.scheduler_stats()
        self._m_occupancy.set(sched["block_occupancy"])
        self._m_prefix_rate.set(sched["prefix_hit_rate"])
        self._m_occ_target.set(sched["block_occupancy_target"])
        self._m_occ_draft.set(sched["block_occupancy_draft"])
        for c, depth in sched["queue_depth"].items():
            g = self._m_class_depth.get(c)
            if g is not None:
                g.set(depth)
        if self._spec:
            gamma = sched["speculative"]["gamma"]
            self._m_gamma.set(gamma)
            self._m_gamma_hist.observe(float(gamma))

    # -- graceful drain ----------------------------------------------------

    @property
    def draining(self) -> bool:
        return self._draining

    def drain(self) -> None:
        """Enter DRAINING: new submits answer 429 with ``"draining":
        true``; in-flight work runs to completion.  Lock-free (a bool
        flip) so it is safe from a signal handler."""
        if not self._draining:
            self._draining = True
            self._m_draining.set(1)
            from autodist_tpu.telemetry import emit_event
            emit_event("serving/drain", phase="start",
                       outstanding=len(self._outstanding))
            logging.info("EngineServer: draining (%d in flight)",
                         len(self._outstanding))

    def undrain(self) -> None:
        """Leave DRAINING and admit again (rollback of an aborted
        rolling restart)."""
        if self._draining:
            self._draining = False
            self._m_draining.set(0)
            from autodist_tpu.telemetry import emit_event
            emit_event("serving/drain", phase="undrain")

    def idle(self) -> bool:
        """True when nothing is in flight (the drained-exit condition)."""
        return not self._outstanding

    # -- request plumbing (called from handler threads) --------------------

    def _locked(self):
        """Handler-side lock acquisition, counted so the driver loop
        yields to it (see ``_handler_waiters``)."""
        return _CountedLock(self)

    def _submit(self, prompt: np.ndarray, max_new: int,
                temperature=None, eos_id=None,
                use_prefix: bool = False, slo: Optional[str] = None,
                trace_id: str = "", gamma: Optional[int] = None,
                deadline_s: Optional[float] = None) -> int:
        with self._locked():
            if self._stop or self._engine_error is not None:
                raise _Unavailable()
            if self._draining:
                raise _Draining(self._drain_retry_hint())
            self._m_queue.observe(float(len(self._outstanding)))
            kwargs = dict(temperature=temperature, eos_id=eos_id,
                          use_prefix=use_prefix)
            if trace_id and self._paged:
                # Only the paged scheduler records per-request spans;
                # the slot engine ignores trace ids (its submit has no
                # per-request lifecycle timestamps to span).
                kwargs["trace_id"] = trace_id
            if gamma is not None:
                if not self._spec:
                    raise ValueError(
                        "this server's engine is not speculative; "
                        "drop the gamma field")
                kwargs["gamma"] = gamma
            if slo is not None:
                if not self._paged:
                    raise ValueError(
                        "this server's engine has no SLO classes "
                        "(slot engine); drop the slo field")
                kwargs["slo"] = slo
            if deadline_s is not None:
                if not self._paged:
                    raise ValueError(
                        "this server's engine has no deadline support "
                        "(slot engine); drop the deadline_s field")
                kwargs["deadline_s"] = deadline_s
            rid = self._engine.submit(prompt, max_new, **kwargs)
            self._n_submitted += 1
            self._outstanding.add(rid)
            self._m_outstanding.set(len(self._outstanding))
            self._events[rid] = threading.Event()
            self._work.notify()
            return rid

    def _drain_retry_hint(self) -> float:
        """Retry-After for drain rejections: long enough for the
        rolling restart's relaunch, short enough that the router's next
        attempt lands on the fresh process."""
        hint = getattr(self._engine, "_retry_hint", None)
        return float(hint()) if callable(hint) else 1.0

    def _wait(self, rid: int, timeout_s: float) -> Any:
        """Block until ``rid`` is harvested; returns its tokens.  Waits
        on the request's own Event (no shared-lock contention)."""
        with self._locked():
            ev = self._events.get(rid)
        if ev is not None and not ev.wait(timeout=timeout_s):
            with self._locked():
                # Re-check under the lock: the driver may have set the
                # event between the timeout and here.
                if rid not in self._done:
                    # Nobody is waiting any more: cancel (frees the
                    # slot instead of decoding unread tokens) and drop
                    # the bookkeeping so a racing harvest is discarded,
                    # not leaked.
                    self._engine.cancel(rid)
                    self._outstanding.discard(rid)
                    self._events.pop(rid, None)
                    self._m_timeouts.inc()
                    from autodist_tpu.telemetry import emit_event
                    emit_event("serving/timeout", request_id=rid,
                               timeout_s=float(timeout_s))
                    raise _Timeout()
        with self._locked():
            if rid not in self._done:
                raise _Unavailable()   # stop or engine failure
            return self._done.pop(rid)

    def _cancel(self, rid: int) -> bool:
        with self._locked():
            ok = self._engine.cancel(rid)
            if ok and rid in self._outstanding:
                self._outstanding.discard(rid)
                self._done[rid] = _CANCELLED
                ev = self._events.pop(rid, None)
                if ev is not None:
                    ev.set()
            return ok

    def _snapshot(self, rid: int):
        """Streaming read: (tokens_so_far, done) for an in-flight rid."""
        with self._locked():
            if rid in self._done:
                return self._done[rid], True
            if self._engine_error is not None or self._stop:
                raise _Unavailable()
            part = self._engine.partial(rid)
            return part, False

    def _finish_stream(self, rid: int) -> Any:
        with self._locked():
            self._events.pop(rid, None)
            return self._done.pop(rid, None)

    def count_request(self, *, served: bool,
                      latency_s: Optional[float] = None) -> None:
        """Bump the served/failed counter (handler threads race here;
        '+=' alone loses updates); ``latency_s`` feeds the request
        latency histogram when the terminal path knows it."""
        with self._meta_lock:
            if served:
                self.requests_served += 1
            else:
                self.requests_failed += 1
        (self._m_served if served else self._m_failed).inc()
        if latency_s is not None:
            self._m_latency.observe(latency_s)
        self._m_outstanding.set(len(self._outstanding))

    def stats(self) -> Dict[str, Any]:
        if self._chaos and self._chaos.stats_stale \
                and self._stale_stats is not None:
            # stale_stats chaos: the router keeps scoring off this
            # frozen snapshot — the load-balancing-blind drill.
            return dict(self._stale_stats)
        with self._locked():
            # Counters accumulate numpy scalars (+= np.int32); coerce so
            # json.dumps never trips on a dtype.
            st = {k: int(v) for k, v in asdict(self._engine.stats).items()
                  if not k.startswith("_")}
            st["slot_utilization"] = round(
                self._engine.stats.slot_utilization, 4)
            st["outstanding"] = len(self._outstanding)
            st["requests_served"] = self.requests_served
            st["requests_failed"] = self.requests_failed
            st["engine_failed"] = self._engine_error is not None
            p50 = self._m_latency.percentile(0.5)
            p99 = self._m_latency.percentile(0.99)
            if p50 is not None:
                st["latency_p50_ms"] = round(p50 * 1e3, 3)
                st["latency_p99_ms"] = round(p99 * 1e3, 3)
            if self._paged:
                # scheduler surface: per-class queue depth, block-pool
                # occupancy, prefix hit rate (the router's load score
                # reads these)
                st.update(self._engine.scheduler_stats())
                p50 = self._m_ttft.percentile(0.5)
                if p50 is not None:
                    st["ttft_p50_ms"] = round(p50 * 1e3, 3)
                    st["ttft_p99_ms"] = round(
                        self._m_ttft.percentile(0.99) * 1e3, 3)
            st["draining"] = self._draining
            if self._chaos and self._chaos.stats_stale:
                self._stale_stats = dict(st)
            return st

    def render_metrics(self) -> str:
        """Prometheus text exposition of the server registry (the
        ``/metrics`` scrape body)."""
        return render_prometheus(self._registry)

    # -- body parsing ------------------------------------------------------

    def parse_prompt(self, body: Dict[str, Any]) -> np.ndarray:
        if "prompt_tokens" in body:
            toks = body["prompt_tokens"]
            # type(t) is int, not isinstance: bool subclasses int, and
            # true/false must be a 400, not token ids 1/0.
            if (not isinstance(toks, list) or not toks
                    or not all(type(t) is int for t in toks)):
                raise ValueError(
                    "prompt_tokens must be a non-empty list of ints")
            return np.asarray(toks, np.int32)
        if "prompt" in body:
            if self._tokenizer is None:
                raise ValueError(
                    "server has no tokenizer: send prompt_tokens "
                    "(a list of token ids) instead of prompt text")
            return np.asarray(self._tokenizer.encode(body["prompt"]),
                              np.int32)
        raise ValueError("body needs prompt_tokens (or prompt, "
                         "with a tokenizer installed)")

    def render(self, rid: int, tokens: np.ndarray,
               prompt_len: int) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "id": rid,
            "tokens": [int(t) for t in tokens],
            "new_tokens": [int(t) for t in tokens[prompt_len:]],
        }
        if self._tokenizer is not None:
            out["text"] = self._tokenizer.decode(out["tokens"])
        return out


class _CountedLock:
    """Context manager acquiring the server lock with the handler-waiter
    count bumped, so the driver loop yields between iterations."""

    def __init__(self, srv: "EngineServer"):
        self._srv = srv

    def __enter__(self):
        with self._srv._meta_lock:
            self._srv._handler_waiters += 1
        try:
            self._srv._lock.acquire()
        finally:
            with self._srv._meta_lock:
                self._srv._handler_waiters -= 1

    def __exit__(self, *exc):
        self._srv._lock.release()


class _Unavailable(Exception):
    pass


class _Timeout(Exception):
    pass


class _Draining(Exception):
    """Submit refused: the replica is draining (429 + draining flag)."""

    def __init__(self, retry_after_s: float = 1.0):
        super().__init__("replica is draining")
        self.retry_after_s = float(retry_after_s)


class _Handler(BaseHTTPRequestHandler):
    # Quiet the default per-request stderr lines; route to our logger.
    def log_message(self, fmt, *args):   # noqa: N802 (stdlib name)
        logging.debug("EngineServer http: " + fmt, *args)

    def _json(self, code: int, payload: Dict[str, Any],
              headers: Optional[Dict[str, str]] = None) -> None:
        data = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)

    def _body(self) -> Dict[str, Any]:
        n = int(self.headers.get("Content-Length", 0))
        if n > _MAX_BODY_BYTES:
            raise ValueError(f"body too large ({n} bytes)")
        body = json.loads(self.rfile.read(n) or b"{}")
        if not isinstance(body, dict):
            raise ValueError("body must be a JSON object")
        return body

    def _text(self, code: int, body: str,
              content_type: str = "text/plain; version=0.0.4") -> None:
        data = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self) -> None:   # noqa: N802
        srv: EngineServer = self.server.owner
        if self.path == "/healthz":
            self._json(200, {"ok": srv._engine_error is None
                             and not srv._stop})
        elif self.path == "/v1/stats":
            self._json(200, srv.stats())
        elif self.path == "/metrics":
            self._text(200, srv.render_metrics())
        else:
            self._json(404, {"error": f"unknown path {self.path}"})

    def do_POST(self) -> None:   # noqa: N802
        srv: EngineServer = self.server.owner
        try:
            body = self._body()
        except (ValueError, json.JSONDecodeError) as e:
            self._json(400, {"error": str(e)})
            return
        if self.path == "/v1/completions":
            self._completions(srv, body)
        elif self.path == "/v1/cancel":
            rid = body.get("id")
            if not isinstance(rid, int):
                self._json(400, {"error": "cancel needs an integer id"})
            else:
                self._json(200, {"id": rid,
                                 "cancelled": srv._cancel(rid)})
        elif self.path == "/admin/drain":
            srv.drain()
            self._json(200, {"draining": True,
                             "outstanding": len(srv._outstanding)})
        elif self.path == "/admin/undrain":
            srv.undrain()
            self._json(200, {"draining": False})
        else:
            self._json(404, {"error": f"unknown path {self.path}"})

    def _completions(self, srv: EngineServer, body: Dict[str, Any]) -> None:
        t0 = time.perf_counter()
        t0_unix = time.time()
        # Trace propagation (docs/observability.md): the router stamps
        # X-Autodist-Trace; a bare client gets a fresh id.  The id rides
        # to the scheduler (queue-wait/prefill/decode spans) and back in
        # the response, so one request correlates across hosts in the
        # exported trace.
        trace_id = str(self.headers.get("X-Autodist-Trace", "")
                       or uuid.uuid4().hex[:16])
        try:
            prompt = srv.parse_prompt(body)
            max_new = body.get("max_new_tokens", 16)
            if type(max_new) is not int:   # bool is an int subclass
                raise ValueError("max_new_tokens must be an int")
            temperature = body.get("temperature")
            if temperature is not None and \
                    type(temperature) not in (int, float):
                raise ValueError("temperature must be a number")
            eos_id = body.get("eos_id")
            if eos_id is not None and type(eos_id) is not int:
                raise ValueError("eos_id must be an int")
            use_prefix = body.get("use_prefix", False)
            if type(use_prefix) is not bool:
                raise ValueError("use_prefix must be a bool")
            slo = body.get("slo")
            if slo is not None and not isinstance(slo, str):
                raise ValueError("slo must be a string")
            gamma = body.get("gamma")
            if gamma is not None and type(gamma) is not int:
                raise ValueError("gamma must be an int")
            deadline_s = body.get("deadline_s")
            if deadline_s is not None:
                if type(deadline_s) not in (int, float) \
                        or deadline_s <= 0:
                    raise ValueError("deadline_s must be a number > 0")
                deadline_s = float(deadline_s)
            rid = srv._submit(prompt, max_new, temperature=temperature,
                              eos_id=eos_id, use_prefix=use_prefix,
                              slo=slo, trace_id=trace_id, gamma=gamma,
                              deadline_s=deadline_s)
        except _Unavailable:
            self._json(503, {"error": "engine unavailable"})
            return
        except _Draining as e:
            # Graceful drain: refuse with the draining flag so the
            # router routes elsewhere WITHOUT marking the replica
            # down — it is healthy, just leaving rotation.
            srv.count_request(served=False)
            srv._m_drain_refused.inc()
            retry = max(round(e.retry_after_s, 3), 0.1)
            self._json(429, {"error": "replica is draining",
                             "draining": True, "retry_after_s": retry},
                       headers={"Retry-After": str(int(retry) + 1)})
            return
        except DeadlineError as e:
            # Deadline shed: measured service rates say this request
            # cannot finish in time.  503 + shed flag: route-elsewhere
            # territory (another replica may be less loaded), not a
            # health failure.
            srv.count_request(served=False)
            srv._m_shed.inc()
            retry = max(round(e.retry_after_s, 3), 0.1)
            self._json(503, {"error": str(e), "shed": True,
                             "retry_after_s": retry},
                       headers={"Retry-After": str(int(retry) + 1)})
            return
        except AdmissionError as e:
            # Typed backpressure: the bounded queue rejected the
            # request.  429 + Retry-After so well-behaved clients (and
            # the router) back off or route elsewhere instead of
            # piling on.
            srv.count_request(served=False)
            retry = max(round(e.retry_after_s, 3), 0.1)
            self._json(429, {"error": str(e), "retry_after_s": retry},
                       headers={"Retry-After": str(int(retry) + 1)})
            return
        except ValueError as e:   # engine/body validation, loud and typed
            srv.count_request(served=False)
            self._json(400, {"error": str(e)})
            return
        if body.get("stream"):
            self._stream(srv, rid, prompt.size, t0)
            return
        try:
            tokens = srv._wait(rid, srv._timeout)
        except _Timeout:
            srv.count_request(served=False,
                              latency_s=time.perf_counter() - t0)
            retry = max(round(srv._drain_retry_hint(), 3), 0.1)
            # Retry-After on 504 too: a timed-out-and-cancelled request
            # is load shedding just like the 429 path — tell the
            # client when the replica expects headroom.
            self._json(504, {"error": f"request {rid} timed out and was "
                             f"cancelled", "id": rid,
                             "retry_after_s": retry},
                       headers={"Retry-After": str(int(retry) + 1)})
            return
        except _Unavailable:
            srv.count_request(served=False,
                              latency_s=time.perf_counter() - t0)
            self._json(503, {"error": "engine unavailable", "id": rid})
            return
        if tokens is _DEADLINE:
            info = srv._deadline_info.pop(rid, {})
            srv.count_request(served=False,
                              latency_s=time.perf_counter() - t0)
            retry = max(round(srv._drain_retry_hint(), 3), 0.1)
            self._json(504, {"error": f"request {rid} missed its "
                             f"deadline and was cancelled", "id": rid,
                             "deadline_exceeded": True,
                             "phase": info.get("phase", ""),
                             "retry_after_s": retry},
                       headers={"Retry-After": str(int(retry) + 1)})
            return
        if tokens is _CANCELLED:
            # counted as failed so served+failed covers every handled
            # completion request
            srv.count_request(served=False,
                              latency_s=time.perf_counter() - t0)
            self._json(409, {"error": f"request {rid} was cancelled",
                             "id": rid})
            return
        if srv._chaos and srv._chaos.take_drop():
            # drop_response chaos: the engine finished the work but the
            # client never hears — sever the connection so the caller
            # sees a mid-request transport failure (the retry-
            # idempotence drill).
            srv.count_request(served=False,
                              latency_s=time.perf_counter() - t0)
            self.close_connection = True
            return
        latency = time.perf_counter() - t0
        srv.count_request(served=True, latency_s=latency)
        from autodist_tpu.telemetry.profiler import record_span
        record_span("request", start_unix=t0_unix, dur_s=latency,
                    trace_id=trace_id, request_id=rid)
        payload = srv.render(rid, tokens, prompt.size)
        payload["trace_id"] = trace_id
        self._json(200, payload, headers={"X-Autodist-Trace": trace_id})

    def _stream(self, srv: EngineServer, rid: int, prompt_len: int,
                t0: Optional[float] = None) -> None:
        """SSE: one ``data:`` event per new-token delta, final event
        carries the full result.  Deltas surface at chunk boundaries
        (the engine's streaming granularity, ``DecodeEngine.partial``).
        ``request_timeout_s`` applies here too: an expired stream is
        cancelled (slot freed) with a final timeout event."""
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.end_headers()

        def emit(payload: Dict[str, Any]) -> None:
            self.wfile.write(b"data: " + json.dumps(payload).encode()
                             + b"\n\n")
            self.wfile.flush()

        sent = prompt_len
        deadline = time.monotonic() + srv._timeout
        t0 = time.perf_counter() if t0 is None else t0
        # Exactly-once counting: each terminal path counts, and the
        # OSError handler counts only if no terminal path did (a final
        # emit that fails AFTER counting must not count again).
        counted = False

        def count(*, served: bool) -> None:
            nonlocal counted
            if not counted:
                counted = True
                srv.count_request(served=served,
                                  latency_s=time.perf_counter() - t0)

        try:
            # Announce the request id before any decode progress: a
            # router-side hedger needs the rid EARLY to cancel the
            # losing attempt, and a recovery client uses it to
            # correlate partial tokens (docs/serving.md).
            emit({"id": rid, "done": False, "new_tokens": []})
            while True:
                try:
                    snap, done = srv._snapshot(rid)
                except _Unavailable:
                    count(served=False)
                    emit({"id": rid, "error": "engine unavailable"})
                    return
                if not done and time.monotonic() > deadline:
                    srv._cancel(rid)
                    srv._finish_stream(rid)
                    count(served=False)
                    srv._m_timeouts.inc()
                    from autodist_tpu.telemetry import emit_event
                    emit_event("serving/timeout", request_id=rid,
                               timeout_s=srv._timeout, stream=True)
                    emit({"id": rid, "done": True, "timeout": True})
                    return
                if done:
                    tokens = srv._finish_stream(rid)
                    if tokens is _DEADLINE:
                        srv._deadline_info.pop(rid, None)
                        count(served=False)
                        emit({"id": rid, "done": True,
                              "deadline_exceeded": True})
                    elif tokens is _CANCELLED or tokens is None:
                        count(served=False)
                        emit({"id": rid, "done": True, "cancelled": True})
                    elif srv._chaos and srv._chaos.take_drop():
                        count(served=False)
                        self.close_connection = True
                    else:
                        count(served=True)
                        final = srv.render(rid, tokens, prompt_len)
                        final["done"] = True
                        emit(final)
                    return
                if snap is not None and snap.size > sent:
                    emit({"id": rid, "done": False,
                          "new_tokens": [int(t) for t in snap[sent:]]})
                    sent = int(snap.size)
                time.sleep(0.02)   # poll cadence between chunk boundaries
        except OSError:
            # Any socket write failure — hang-up, abort, timeout — frees
            # the slot instead of decoding tokens nobody will read (and
            # drains the harvested result so it can't leak in _done).
            srv._cancel(rid)
            srv._finish_stream(rid)
            count(served=False)


def install_drain_on_sigterm(server: EngineServer, *,
                             exit_code: Optional[int] = None,
                             settle_s: float = 0.25) -> None:
    """SIGTERM → graceful drain: stop admitting, let in-flight work
    finish, then ``os._exit`` once idle (plus ``settle_s`` for the last
    responses to flush).  The default exit code is the supervisor's
    ``PREEMPTED_EXIT_CODE`` (75): a drained replica relaunches WITHOUT
    consuming restart budget, which is what lets
    ``SupervisedReplicaPool.rolling_restart()`` cycle a whole pool.
    Must be called from the main thread (the ``signal`` module rule —
    raises ``ValueError`` otherwise)."""
    from autodist_tpu.resilience.supervisor import PREEMPTED_EXIT_CODE

    code = PREEMPTED_EXIT_CODE if exit_code is None else int(exit_code)

    def _on_term(signum, frame):
        server.drain()

        def _exit_when_idle():
            while not server.idle():
                time.sleep(0.05)
            time.sleep(settle_s)
            from autodist_tpu.telemetry import emit_event
            emit_event("serving/drain", phase="exit", code=code)
            os._exit(code)

        threading.Thread(target=_exit_when_idle, daemon=True,
                         name="drain-exit").start()

    signal.signal(signal.SIGTERM, _on_term)


def serve(spec, params, *, host: str = "127.0.0.1", port: int = 8000,
          tokenizer=None, prefix_tokens=None, prefix_text=None,
          paged: bool = False, speculative=None,
          drain_on_sigterm: Optional[bool] = None,
          **engine_kwargs) -> EngineServer:
    """Build an engine over ``(spec, params)`` and start an
    :class:`EngineServer` on it.  ``paged=True`` selects the
    paged-KV continuous-batching :class:`PagedDecodeEngine`
    (``serving/scheduler.py``: SLO queues, prefix trie, block pool);
    the default stays the slot engine.  ``engine_kwargs`` pass through
    to the engine (slots, window, chunk, sampling knobs, mesh, ...).  A
    tokenizer with a registered ``<eos>`` special token supplies the
    engine's ``eos_id`` automatically (explicit ``eos_id=`` wins).
    ``prefix_tokens`` (ids) or ``prefix_text`` (tokenizer required)
    registers the shared cached system prompt; requests opt in with
    ``"use_prefix": true``.

    ``speculative`` turns on speculative decoding (docs/serving.md):
    a dict with ``spec`` and ``params`` for the draft model, plus
    optional ``gamma`` (proposal depth, default 4) and ``adapt_gamma``
    (SLO adaptation, default True).  Speculation is a mode of the
    paged scheduler, so it implies ``paged=True``.

    ``drain_on_sigterm`` installs :func:`install_drain_on_sigterm`
    (graceful drain + exit 75 on SIGTERM).  The default (``None``)
    installs it only when the process looks like a supervised replica
    (``AUTODIST_REPLICA_NAME`` in the environment) — a test process
    embedding a server keeps its own signal handling."""
    if "eos_id" not in engine_kwargs:
        eos = getattr(tokenizer, "eos_id", None)
        if eos is not None:
            engine_kwargs["eos_id"] = int(eos)
    if speculative is not None:
        unknown = set(speculative) - {"spec", "params", "gamma",
                                      "adapt_gamma"}
        if unknown or not {"spec", "params"} <= set(speculative):
            raise ValueError(
                "speculative= takes a dict with 'spec' and 'params' "
                f"(optional 'gamma', 'adapt_gamma'); got "
                f"{sorted(speculative)}")
        paged = True
        engine_kwargs["draft_spec"] = speculative["spec"]
        engine_kwargs["draft_params"] = speculative["params"]
        for k in ("gamma", "adapt_gamma"):
            if k in speculative:
                engine_kwargs[k] = speculative[k]
    if paged:
        from autodist_tpu.serving.scheduler import PagedDecodeEngine

        eng = PagedDecodeEngine(spec, params, **engine_kwargs)
    else:
        eng = DecodeEngine(spec, params, **engine_kwargs)
    if prefix_text is not None:
        if tokenizer is None:
            raise ValueError("prefix_text needs a tokenizer; pass "
                             "prefix_tokens instead")
        if prefix_tokens is not None:
            raise ValueError("pass prefix_tokens OR prefix_text")
        prefix_tokens = tokenizer.encode(prefix_text)
    if prefix_tokens is not None:
        eng.set_prefix(prefix_tokens)
    srv = EngineServer(eng, host=host, port=port,
                       tokenizer=tokenizer).start()
    if drain_on_sigterm is None:
        drain_on_sigterm = bool(os.environ.get("AUTODIST_REPLICA_NAME"))
    if drain_on_sigterm:
        try:
            install_drain_on_sigterm(srv)
        except ValueError:   # not the main thread: skip, best-effort
            logging.warning("serve(): cannot install the SIGTERM drain "
                            "handler off the main thread")
    return srv
