"""Paged KV cache for serving: block pool, prefix trie, paged programs.

The slot engine (``serving/engine.py``) holds one contiguous ring buffer
``[L, window, slots, H, Dh]`` — every slot owns a window-sized region
for its whole lifetime, and the only prefix reuse is ONE registered
system prompt.  This module replaces that memory story with the
production paged layout (the vLLM design point, adapted to the repo's
static-shape TPU rules):

* **Block pool.**  K/V live in ``[L, num_blocks, block_size, H, Dh]``
  pools.  A request's cache is a *block table* — the list of physical
  blocks backing its logical token positions — so freed requests return
  blocks to the pool immediately instead of holding a slot-shaped
  region, and total KV memory is sized to live tokens, not
  ``slots x window``.
* **Refcounted sharing + COW.**  Blocks are refcounted
  (:class:`BlockPool`): a full prompt block can back many requests at
  once.  Sharing is read-only by construction — the trie never shares a
  request's *last* prompt block, so every write a request performs
  (suffix prefill, decode appends) lands in blocks it owns alone —
  and :meth:`BlockPool.cow` is the guarded write path for anything
  else: writing a shared block first clones it.
* **Prefix trie.**  :class:`PrefixTrie` maps chains of full token
  blocks to cached pool blocks (copy-on-write semantics over the
  refcounts): a request whose prompt starts with a cached chain skips
  recomputing those blocks entirely — its prefill runs only over the
  suffix, attending the cached blocks through its block table.  This
  generalizes the old single ``set_prefix`` slot to arbitrary
  multi-tenant shared prefixes; refcount-zero cached blocks are LRU
  material when the pool runs dry.
* **Paged device programs.**  ``_paged_chunk_program`` /
  ``_paged_prefill_program`` mirror the slot engine's programs with the
  block table as a TRACED input: per-tick K/V writes scatter through
  ``(table[pos // bs], pos % bs)`` and attention gathers each slot's
  window from the pool.  The indirection costs a gather per layer per
  tick (the ring design's uniform contiguous write is exactly what
  paging gives up — on real TPUs this is where a paged-attention
  kernel goes); what it buys is admission decoupled from memory shape:
  any free slot plus enough free blocks admits any request, and block
  tables never force a recompile (they are data, not shape).  With
  ``AUTODIST_FUSED_KERNELS=paged_attention`` the decode program drops
  the gather entirely: the fused Pallas kernel
  (``ops/fused_kernels.py``, docs/kernels.md) reads K/V straight
  through the block table via scalar-prefetch index maps with the
  flash-attention online-softmax structure; off-TPU the gather path
  stays, with a shared drop-reason WARN.

Numerics are the same single-definition ``TransformerLayer`` math as
training/decode (the ``attn_fn`` seam), so greedy paged output equals
the per-request ``generate`` oracle exactly — pinned in
``tests/test_serving_scheduler.py``.
"""
from __future__ import annotations

import functools
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from autodist_tpu.models.generate import unpack_lm_params
from autodist_tpu.models.quantize import (embed_lookup, head_logits,
                                          quant_interceptor)
from autodist_tpu.models.transformer import TransformerLayer
from autodist_tpu.ops.quant import Quantized
from autodist_tpu.serving.engine import _sample_per_slot

#: physical block 0 is reserved as the scratch target: device programs
#: redirect every masked-out write (dead slots, pad rows) there, so a
#: freed block can be handed to a new owner between dispatches without
#: any risk of a stale slot scribbling on it.
SCRATCH_BLOCK = 0

_paged_kernel_warned = False


def _use_fused_paged_attention() -> bool:
    """Does this trace lower decode attention through the fused Pallas
    paged-attention kernel (``ops/fused_kernels.py``, opted in via
    ``AUTODIST_FUSED_KERNELS=paged_attention``)?  Resolved at TRACE
    time — the jit cache pins the decision per program, like every
    other static knob of ``_paged_chunk_program``.  A requested kernel
    this platform cannot run falls back to the gather-per-layer path
    with one shared drop-reason WARN."""
    global _paged_kernel_warned
    from autodist_tpu.ops import fused_kernels as fk
    from autodist_tpu.utils import logging

    active, why = fk.paged_attention_status()
    if why is not None and not _paged_kernel_warned:
        _paged_kernel_warned = True
        logging.warning(
            "paged decode: fused paged-attention kernel falls back to "
            "the gather-per-layer program (%s)", why)
    return active


class BlockPoolExhausted(RuntimeError):
    """alloc() could not be satisfied even after trie eviction."""


@dataclass
class BlockPoolStats:
    allocs: int = 0               # blocks handed out
    frees: int = 0                # blocks returned to the free list
    cow_copies: int = 0           # shared-block writes that cloned
    exhaustions: int = 0          # alloc() failures (pool dry)
    high_water: int = 0           # max blocks simultaneously in use


class BlockPool:
    """Host-side allocator over the physical KV blocks.

    Pure bookkeeping — the device arrays live with the engine; the pool
    tracks which physical block indices are free, each block's
    refcount, and the alloc/free/COW invariants the tests pin:

    * a block is either free (refcount 0, on the free list) or held
      (refcount >= 1), never both;
    * ``release`` frees exactly when the last reference drops;
    * ``cow`` returns the block itself when exclusively held and a
      fresh block (dropping one reference on the shared one) when not;
    * block 0 (:data:`SCRATCH_BLOCK`) is reserved and never allocated.
    """

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError("BlockPool needs >= 2 blocks (one is the "
                             "reserved scratch block)")
        if block_size < 1:
            raise ValueError("block_size must be >= 1")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        # LIFO free list: recently freed blocks are re-used first (their
        # pool regions are most likely still resident in cache).
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._refs: List[int] = [0] * num_blocks
        self.stats = BlockPoolStats()

    # -- capacity ----------------------------------------------------------
    @property
    def capacity(self) -> int:
        """Allocatable blocks (excludes the scratch block)."""
        return self.num_blocks - 1

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        return self.capacity - self.free_count

    def occupancy(self) -> float:
        return self.used_count / self.capacity if self.capacity else 0.0

    def blocks_for_tokens(self, tokens: int) -> int:
        return -(-int(tokens) // self.block_size)

    # -- alloc / refcount --------------------------------------------------
    def alloc(self, n: int) -> List[int]:
        """Allocate ``n`` blocks (each at refcount 1) or raise
        :class:`BlockPoolExhausted` allocating NONE (all-or-nothing, so
        a failed admission never leaks a partial allocation)."""
        if n < 0:
            raise ValueError("alloc needs n >= 0")
        if n > len(self._free):
            self.stats.exhaustions += 1
            raise BlockPoolExhausted(
                f"need {n} blocks, {len(self._free)} free "
                f"(capacity {self.capacity})")
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._refs[b] = 1
        self.stats.allocs += n
        self.stats.high_water = max(self.stats.high_water, self.used_count)
        return out

    def retain(self, block: int) -> None:
        if self._refs[block] < 1:
            raise ValueError(f"retain on unallocated block {block}")
        self._refs[block] += 1

    def release(self, block: int) -> bool:
        """Drop one reference; True when this freed the block."""
        if block == SCRATCH_BLOCK:
            raise ValueError("release on the reserved scratch block")
        if self._refs[block] < 1:
            raise ValueError(f"release on free block {block} "
                             "(double free)")
        self._refs[block] -= 1
        if self._refs[block] == 0:
            self._free.append(block)
            self.stats.frees += 1
            return True
        return False

    def refcount(self, block: int) -> int:
        return self._refs[block]

    def cow(self, block: int) -> Tuple[int, bool]:
        """Copy-on-write guard for writing ``block``: exclusively held
        blocks are returned as-is; shared blocks allocate a fresh block
        (the caller must copy the device data), dropping one reference
        on the shared original.  Returns ``(writable_block, copied)``."""
        if self._refs[block] < 1:
            raise ValueError(f"cow on unallocated block {block}")
        if self._refs[block] == 1:
            return block, False
        (fresh,) = self.alloc(1)
        self.release(block)
        self.stats.cow_copies += 1
        return fresh, True

    def verify(self) -> None:
        """Leak/corruption check: every block is exactly free or held,
        and the free list is duplicate-free.  Raises AssertionError."""
        free = set(self._free)
        assert len(free) == len(self._free), "duplicate blocks on free list"
        assert SCRATCH_BLOCK not in free, "scratch block on free list"
        for b in range(1, self.num_blocks):
            if b in free:
                assert self._refs[b] == 0, \
                    f"block {b} free but refcount {self._refs[b]}"
            else:
                assert self._refs[b] >= 1, \
                    f"block {b} leaked (not free, refcount 0)"


@dataclass
class _TrieNode:
    key: Tuple[int, ...]                    # the block's tokens
    block: int
    parent: Optional["_TrieNode"]
    children: Dict[Tuple[int, ...], "_TrieNode"] = field(
        default_factory=dict)
    last_used: float = field(default_factory=time.monotonic)


@dataclass
class PrefixTrieStats:
    hit_blocks: int = 0           # cached blocks handed to requests
    hit_tokens: int = 0
    lookups: int = 0
    lookup_hits: int = 0          # lookups that matched >= 1 block
    inserts: int = 0              # blocks newly cached
    evictions: int = 0            # cached blocks dropped under pressure


class PrefixTrie:
    """Radix cache over FULL prompt blocks.

    Each node caches one block's worth of tokens; a path from the root
    is a prompt prefix whose K/V already live in the pool.  The trie
    holds one pool reference per cached block (so a cached block
    survives its computing request); a matching request retains each
    matched block again for its own lifetime.  Only chains of FULL
    blocks are cached, and a match never covers the whole prompt
    (``match`` caps at ``floor((P-1)/bs)`` blocks) so every request
    prefills at least one suffix token — which also guarantees no
    request ever WRITES a shared block: its writes start at or after
    its suffix, which begins past the shared region.

    Eviction is LRU over refcount-1 leaf nodes — blocks only the trie
    still holds ("refcount-zero" from the requests' point of view);
    interior nodes wait for their children (a chain must stay
    root-connected to be matchable).
    """

    def __init__(self, pool: BlockPool):
        self._pool = pool
        self._root_children: Dict[Tuple[int, ...], _TrieNode] = {}
        self._count = 0
        self.stats = PrefixTrieStats()

    def __len__(self) -> int:
        return self._count

    def _chunks(self, tokens, limit_blocks: int):
        bs = self._pool.block_size
        out = []
        for i in range(limit_blocks):
            out.append(tuple(int(t) for t in tokens[i * bs:(i + 1) * bs]))
        return out

    def match(self, tokens) -> Tuple[int, List[int]]:
        """Longest cached chain covering ``tokens`` (capped to leave at
        least one suffix token uncovered).  Returns ``(n_cached_tokens,
        block_ids)`` with each returned block RETAINED for the caller —
        symmetric with the caller releasing every block of its table on
        completion."""
        bs = self._pool.block_size
        p = len(tokens)
        limit = max((p - 1) // bs, 0)
        self.stats.lookups += 1
        now = time.monotonic()
        blocks: List[int] = []
        children = self._root_children
        for key in self._chunks(tokens, limit):
            node = children.get(key)
            if node is None:
                break
            node.last_used = now
            self._pool.retain(node.block)
            blocks.append(node.block)
            children = node.children
        if blocks:
            self.stats.lookup_hits += 1
            self.stats.hit_blocks += len(blocks)
            self.stats.hit_tokens += len(blocks) * bs
        return len(blocks) * bs, blocks

    def insert(self, tokens, table: List[int]) -> int:
        """Cache the full prompt blocks of a request whose K/V for
        ``tokens`` now live in ``table`` (its block table, in logical
        order).  Blocks newly cached are retained by the trie; chunks
        already cached are skipped (first writer wins — the duplicate
        block stays owned by its request alone and frees with it).
        Returns how many blocks were newly cached."""
        limit = max((len(tokens) - 1) // self._pool.block_size, 0)
        limit = min(limit, len(table))
        added = 0
        children = self._root_children
        parent: Optional[_TrieNode] = None
        for i, key in enumerate(self._chunks(tokens, limit)):
            node = children.get(key)
            if node is None:
                node = _TrieNode(key=key, block=table[i], parent=parent)
                self._pool.retain(table[i])
                children[key] = node
                self._count += 1
                added += 1
            children = node.children
            parent = node
        self.stats.inserts += added
        return added

    def evict(self, n: int) -> int:
        """Release up to ``n`` cached blocks, LRU-first among leaf
        nodes whose block only the trie still references.  Returns how
        many blocks were actually freed to the pool."""
        freed = 0
        while freed < n:
            victim = None
            for node in self._iter_nodes():
                if node.children:
                    continue                        # interior: keep chain
                if self._pool.refcount(node.block) != 1:
                    continue                        # pinned by a request
                if victim is None or node.last_used < victim.last_used:
                    victim = node
            if victim is None:
                break
            self._remove(victim)
            self._pool.release(victim.block)
            self.stats.evictions += 1
            freed += 1
        return freed

    def clear(self) -> int:
        """Drop the whole cache (releases every trie reference —
        blocks still pinned by in-flight requests stay alive until
        those requests finish).  Returns blocks released."""
        n = 0
        for node in list(self._iter_nodes()):
            self._pool.release(node.block)
            n += 1
        self._root_children.clear()
        self._count = 0
        return n

    def cached_blocks(self) -> List[int]:
        return [node.block for node in self._iter_nodes()]

    def _iter_nodes(self):
        stack = list(self._root_children.values())
        while stack:
            node = stack.pop()
            yield node
            stack.extend(node.children.values())

    def _remove(self, node: _TrieNode) -> None:
        siblings = (node.parent.children if node.parent is not None
                    else self._root_children)
        del siblings[node.key]
        self._count -= 1


# ---------------------------------------------------------------------------
# device programs (module scope: the jit cache is shared across engines)
# ---------------------------------------------------------------------------

def _paged_token_step(layer_params, ln_final_scale, embed, x, kc, vc,
                      bt, blk, off, rel):
    """One decode position through all layers over the PAGED cache.

    ``kc``/``vc``: [L, NB, BS, H, Dh] pools; ``bt``: [B, MAXB] block
    table; ``blk``/``off``: [B] physical write coordinates for this
    tick (pre-masked: dead slots point at the scratch block); ``rel``:
    [B] logical sequence position.  Same shared ``TransformerLayer``
    block math as ``generate._token_step`` — only the cache addressing
    differs: the write scatters through the table and attention gathers
    each slot's logical window ``take(pool, bt)`` before the usual
    masked softmax (extra masked positions contribute exactly-zero
    weight, so numerics match the contiguous layouts)."""
    heads, hd = kc.shape[-2], kc.shape[-1]
    bs = kc.shape[2]
    b, maxb = bt.shape
    w = maxb * bs
    d_ff = layer_params[0]["mlp"]["wi"]["kernel"].shape[1]
    quantized = isinstance(layer_params[0]["mlp"]["wi"]["kernel"],
                           Quantized)
    x = x[:, None, :]                                   # [B, 1, D]
    mask = jnp.arange(w)[None, None, :] <= rel[:, None, None]  # [B,1,W]
    fused_attn = _use_fused_paged_attention()
    for i, lp in enumerate(layer_params):
        cache_out = {}

        def paged_attn(q, k, v, causal, _i=i, _out=cache_out):
            kcn = kc.at[_i, blk, off].set(k[:, 0].astype(kc.dtype))
            vcn = vc.at[_i, blk, off].set(v[:, 0].astype(vc.dtype))
            _out["k"], _out["v"] = kcn, vcn
            if fused_attn:
                # Fused paged-attention kernel (docs/kernels.md): the
                # block table drives scalar-prefetch index maps, so the
                # kernel DMAs exactly the physical blocks each slot's
                # window names — no [B, W, H, Dh] gather materialized
                # per layer per tick.
                from autodist_tpu.ops.fused_kernels import paged_attention
                return paged_attention(q[:, 0], kcn[_i], vcn[_i], bt,
                                       rel)[:, None]
            # each slot's logical window, gathered from the pool
            kb = jnp.take(kcn[_i], bt, axis=0).reshape(b, w, heads, hd)
            vb = jnp.take(vcn[_i], bt, axis=0).reshape(b, w, heads, hd)
            depth = q.shape[-1]
            logits = jnp.einsum("bhk,bwhk->bhw", q[:, 0],
                                kb.astype(q.dtype)) \
                / jnp.sqrt(jnp.asarray(depth, q.dtype))
            logits = jnp.where(mask, logits, jnp.finfo(logits.dtype).min)
            probs = jax.nn.softmax(logits.astype(jnp.float32),
                                   axis=-1).astype(q.dtype)
            return jnp.einsum("bhw,bwhk->bhk", probs,
                              vb.astype(q.dtype))[:, None]

        layer = TransformerLayer(heads, hd, d_ff, causal=True,
                                 attn_fn=paged_attn)
        if quantized:
            with nn.intercept_methods(quant_interceptor(lp)):
                x = layer.apply({"params": lp}, x)
        else:
            x = layer.apply({"params": lp}, x)
        kc, vc = cache_out["k"], cache_out["v"]
    x = nn.LayerNorm(use_bias=False).apply(
        {"params": {"scale": ln_final_scale}}, x)
    return head_logits(embed, x[:, 0]), kc, vc


@functools.partial(jax.jit, static_argnums=(0, 1),
                   donate_argnums=(3, 4, 5))
def _paged_chunk_program(n, knobs, params, tokens, kc, vc, bt, start,
                         p_end, end, done, active, temp, eos, tick0,
                         key):
    """``n`` decode ticks of all slots in lockstep over the paged pool.

    The paged analog of ``engine._chunk_program``: positions are
    LOGICAL (``rel = tick - start``, no ring — the block table is the
    indirection), token reads/writes index each slot's row at its own
    ``rel``, and K/V writes route through the table with dead slots
    redirected to the scratch block (a freed block may already belong
    to someone else).  ``knobs`` = (top_k, top_p, block_size)."""

    top_k, top_p, bs = knobs
    num_layers = kc.shape[0]
    slots, w = tokens.shape
    embed, pos_embed, layer_params, ln_final = unpack_lm_params(
        params, num_layers)
    rows = jnp.arange(slots)

    def one_tick(carry, i):
        tokens, kc, vc, done, key = carry
        t = tick0 + i
        rel = jnp.clip(t - start, 0, w - 1)               # [B] logical pos
        tok = jnp.take_along_axis(tokens, rel[:, None], 1)[:, 0]
        x = embed_lookup(embed, tok, pos_embed.dtype) + pos_embed[rel]
        live = active & ~done
        blk = jnp.where(
            live,
            jnp.take_along_axis(bt, rel[:, None] // bs, 1)[:, 0],
            SCRATCH_BLOCK)
        logits, kc, vc = _paged_token_step(
            layer_params, ln_final, embed, x, kc, vc, bt, blk,
            jnp.mod(rel, bs), rel)
        key, sub = jax.random.split(key)
        raw = _sample_per_slot(logits, sub, temp, top_k,
                               top_p).astype(tokens.dtype)
        busy = jnp.sum(live.astype(jnp.int32))
        w_pos = jnp.clip(rel + 1, 0, w - 1)
        cur = jnp.take_along_axis(tokens, w_pos[:, None], 1)[:, 0]
        in_gen = t + 1 >= p_end
        nxt = jnp.where(in_gen & live, raw, cur)
        tokens = tokens.at[rows, w_pos].set(nxt)
        done = done | (in_gen & live & (raw == eos))
        done = done | (t + 2 >= end)
        return (tokens, kc, vc, done, key), busy

    (tokens, kc, vc, done, key), busy = lax.scan(
        one_tick, (tokens, kc, vc, done, key), jnp.arange(n))
    return tokens, kc, vc, done, jnp.sum(busy)


@functools.partial(jax.jit, static_argnums=(0,),
                   donate_argnums=(2, 3, 4))
def _paged_prefill_program(knobs, params, tokens, kc, vc, chunk_kpb,
                           bt_rows, slot_ids, n_shared, c_lens,
                           is_final, temp, key):
    """One prefill CHUNK for K rows: a [K, Pb]-parallel causal forward
    over each row's next ``c_lens[k]`` uncharged prompt tokens, with
    everything already charged — trie-cached prefix blocks AND earlier
    chunks of the same prompt, both addressed by the row's block table
    masked to ``n_shared[k]`` tokens — attended as cached context.
    That one traced mask is what makes prefix reuse and chunked
    prefill the SAME program: a cold prompt runs with ``n_shared=0``, a
    prefix hit starts at the cached length, and a long prompt walks
    ``n_shared`` forward chunk by chunk between decode ticks.

    Suffix K/V scatter into the pool at logical positions
    ``n_shared + j`` through the block table (pad columns to the
    scratch block); rows with ``is_final`` (their last chunk) also
    sample their first generated token from the chunk's last position
    and deposit it at ``n_shared + c_len``.  Duplicate ``slot_ids``
    (pow-2 padding repeats the last row) are resolved by reading back
    the LANDED token, as in the slot engine's prefill.

    Besides the landed token, the program returns ``preds`` — the
    greedy argmax at EVERY chunk position (``preds[k, j]`` is the
    model's token for logical position ``n_shared[k] + j + 1``).
    Prefill callers ignore it; it is what makes multi-token
    speculative VERIFY this same traced program: the scheduler feeds
    the gamma+1 candidate tokens as a chunk with ``n_shared`` at the
    request's committed length, and greedy acceptance falls out of
    comparing ``preds`` against the drafts on the host — no extra
    program cache entries beyond the (gamma-bucketed) chunk length."""

    top_k, top_p, bs = knobs
    num_layers = kc.shape[0]
    heads, hd = kc.shape[-2], kc.shape[-1]
    k_rows, pb = chunk_kpb.shape
    maxb = bt_rows.shape[1]
    w = maxb * bs
    embed, pos_embed, layer_params, ln_final = unpack_lm_params(
        params, num_layers)
    d_ff = layer_params[0]["mlp"]["wi"]["kernel"].shape[1]
    quantized = isinstance(layer_params[0]["mlp"]["wi"]["kernel"],
                           Quantized)
    pos_ids = jnp.clip(n_shared[:, None] + jnp.arange(pb)[None, :], 0,
                       pos_embed.shape[0] - 1)
    x = embed_lookup(embed, chunk_kpb, pos_embed.dtype) \
        + pos_embed[pos_ids]
    ctx_mask = jnp.arange(w)[None, None, None, :] \
        < n_shared[:, None, None, None]                  # [K,1,1,W]
    ks, vs = [], []

    def capture_attn(q, k, v, causal):
        i = len(ks)
        ks.append(k)
        vs.append(v)
        depth = q.shape[-1]
        scale = jnp.sqrt(jnp.asarray(depth, q.dtype))
        sl = jnp.einsum("bqhd,bkhd->bhqk", q, k) / scale
        causal_m = jnp.tril(jnp.ones((pb, pb), bool))
        sl = jnp.where(causal_m, sl, jnp.finfo(sl.dtype).min)
        kb = jnp.take(kc[i], bt_rows, axis=0).reshape(
            k_rows, w, heads, hd).astype(q.dtype)
        vb = jnp.take(vc[i], bt_rows, axis=0).reshape(
            k_rows, w, heads, hd).astype(q.dtype)
        pl = jnp.einsum("bqhd,bphd->bhqp", q, kb) / scale
        pl = jnp.where(ctx_mask, pl, jnp.finfo(sl.dtype).min)
        probs = jax.nn.softmax(
            jnp.concatenate([pl, sl], axis=-1).astype(jnp.float32),
            axis=-1).astype(q.dtype)
        out = jnp.einsum("bhqp,bphd->bqhd", probs[..., :w], vb)
        return out + jnp.einsum("bhqk,bkhd->bqhd", probs[..., w:], v)

    for lp in layer_params:
        layer = TransformerLayer(heads, hd, d_ff, causal=True,
                                 attn_fn=capture_attn)
        if quantized:
            with nn.intercept_methods(quant_interceptor(lp)):
                x = layer.apply({"params": lp}, x)
        else:
            x = layer.apply({"params": lp}, x)
    x = nn.LayerNorm(use_bias=False).apply(
        {"params": {"scale": ln_final}}, x)

    ksl = jnp.stack(ks)                                  # [L, K, Pb, H, Dh]
    vsl = jnp.stack(vs)
    pos = n_shared[:, None] + jnp.arange(pb)[None, :]    # [K, Pb]
    valid = jnp.arange(pb)[None, :] < c_lens[:, None]
    blk = jnp.where(
        valid,
        jnp.take_along_axis(bt_rows, jnp.clip(pos // bs, 0, maxb - 1), 1),
        SCRATCH_BLOCK)
    off = jnp.mod(pos, bs)
    kc = kc.at[:, blk, off].set(ksl.astype(kc.dtype))
    vc = vc.at[:, blk, off].set(vsl.astype(vc.dtype))

    # Every position's logits: the last position's row feeds sampling
    # (the prefill path), the full [K, Pb] argmax is the verify surface.
    all_logits = head_logits(embed, x.reshape(k_rows * pb, -1)) \
        .reshape(k_rows, pb, -1)                         # [K, Pb, V]
    preds = jnp.argmax(all_logits, axis=-1).astype(tokens.dtype)
    logits = jnp.take_along_axis(
        all_logits,
        jnp.clip(c_lens - 1, 0, pb - 1)[:, None, None].astype(jnp.int32),
        axis=1)[:, 0]                                    # [K, V]
    temp_k = jnp.take(temp, slot_ids)
    toks = _sample_per_slot(logits, key, temp_k, top_k, top_p)
    w_pos = jnp.clip(n_shared + c_lens, 0, tokens.shape[1] - 1)
    cur = tokens[slot_ids, w_pos]
    tokens = tokens.at[slot_ids, w_pos].set(
        jnp.where(is_final, toks.astype(tokens.dtype), cur))
    landed = tokens[slot_ids, w_pos]
    return tokens, kc, vc, landed, preds


@functools.partial(jax.jit, donate_argnums=(0,))
def _commit_tokens_program(tokens, rows, pos, vals):
    """Batched point-writes into the device token buffer: one token per
    ``(rows[i], pos[i])`` pair.  The speculative round's bonus-token
    commit — pow-2 padded by repeating the last entry (duplicate writes
    of the same value are idempotent)."""
    return tokens.at[rows, pos].set(vals.astype(tokens.dtype))
