from autodist_tpu.checkpoint.saver import Saver  # noqa: F401
from autodist_tpu.checkpoint.saved_model_builder import SavedModelBuilder  # noqa: F401
from autodist_tpu.checkpoint.tiers import (  # noqa: F401
    CheckpointTiers,
    PeerMirror,
    RamSnapshot,
    SnapshotError,
    SnapshotRing,
    buddy_of,
    capture_snapshot,
    load_snapshot,
    route_restore,
    snapshot_from_bytes,
    snapshot_to_bytes,
)
