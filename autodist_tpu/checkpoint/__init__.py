from autodist_tpu.checkpoint.saver import Saver  # noqa: F401
from autodist_tpu.checkpoint.saved_model_builder import SavedModelBuilder  # noqa: F401
