"""Fast-recovery checkpoint tiers: host-RAM snapshots, peer mirrors,
restore routing (docs/resilience.md, docs/checkpoints.md).

At pod scale, preemptions and host failures are operating conditions,
not exceptions — yet the persistent Orbax tier alone makes every
recovery cost minutes of shared-disk I/O plus up to ``checkpoint_every``
steps of lost work.  This module adds the cheap tiers above it:

* **RAM tier** — every ``snapshot_every`` steps, each host takes a
  device→host snapshot of its process-addressable training state
  (params + optimizer state + sync state, LOGICAL layout — the same
  layout ``Saver`` persists, so the tiers interchange) into an
  in-process :class:`SnapshotRing` of the last ``keep`` snapshots,
  digest-checked with the Saver's content-digest rule.
* **Peer tier** — each snapshot is serialized and mirrored to a buddy
  host (ring mapping: host *i*'s buddy is host *i+1*) over the existing
  ``Cluster`` retry transport (``remote_copy`` — SSH flakes retry with
  the shared ``Backoff``; local addresses degrade to a file copy, which
  is also the CPU-test path).  The mirror directory should be RAM-backed
  in production (``/dev/shm/...``): the tier's entire point is that a
  *replaced* host rejoins from a survivor's memory in seconds, without
  touching persistent storage.
* **Restore routing** — :func:`route_restore` tries RAM-local →
  peer-fetch → persistent, newest usable step wins (cheaper tier on
  ties), composing with ``preflight_elastic`` when a candidate's
  recorded mesh differs from the session's.

Work-loss bound: with a RAM snapshot every K steps, any single-host
failure loses at most K steps (vs ``checkpoint_every`` × steps/epoch
for the persistent tier alone) — the ``resilience/recovery-gap``
analysis rule warns when the persistent cadence alone exceeds the
recovery-loss budget and no RAM tier is configured.

Addressability: the RAM tier snapshots what THIS process can read
(``np.asarray`` of every leaf).  Fully-replicated state (the AllReduce
path) and single-process meshes snapshot whole; a leaf that is not
process-addressable (multi-host GSPMD shards) disables the tier with
one WARN and recovery falls through to the persistent tier — the tier
is an accelerator, never a correctness dependency.  ZeRO-1's flat
optimizer shards ARE host-owned by construction, which is what makes
them the natural unit for this tier (see docs/resilience.md).
"""
from __future__ import annotations

import io
import json
import os
import re
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from autodist_tpu.utils import logging

#: snapshot file name grammar in a peer-mirror directory.
SNAP_RE = re.compile(r"^snap_step_(\d+)\.npz$")

#: route_restore tier names, cheapest first (the tie-break order).
TIER_RAM = "ram"
TIER_PEER = "peer"
TIER_PERSISTENT = "persistent"


class SnapshotError(RuntimeError):
    """A snapshot failed to capture, serialize, or verify."""


def _tree_digest(tree: Any) -> Optional[str]:
    """The Saver's content-digest rule, shared so RAM/peer snapshots and
    persistent checkpoints can never disagree about what 'intact'
    means."""
    from autodist_tpu.checkpoint.saver import _tree_digest as digest

    return digest(tree)


@dataclass
class RamSnapshot:
    """One device→host snapshot: leaves in tree-flatten order per item
    (the restore side unflattens against the session's own target
    treedefs, exactly like a target-free Orbax restore), plus the same
    provenance ``Saver.save`` records."""

    step: int
    leaves: Dict[str, List[np.ndarray]]   # item -> flat leaves
    digest: Optional[str]
    meta: Dict[str, Any] = field(default_factory=dict)
    time: float = 0.0

    @property
    def nbytes(self) -> int:
        return sum(a.nbytes for ls in self.leaves.values() for a in ls)

    def verify(self) -> bool:
        """Recompute the content digest over the held leaves — the
        in-RAM analog of ``Saver.verify(deep=True)``."""
        if self.digest is None:
            return True   # digest was skipped at capture; nothing to check
        return _tree_digest([self.leaves[k]
                             for k in sorted(self.leaves)]) == self.digest


def capture_snapshot(session, step: Optional[int] = None,
                     extra_meta: Optional[dict] = None) -> RamSnapshot:
    """Device→host snapshot of the session's LOGICAL state.

    Synchronous by design (like the Saver's snapshot half): the training
    loop immediately donates/overwrites the live buffers, so the copy
    must complete before the next step dispatches.  Raises
    :class:`SnapshotError` when any leaf is not process-addressable."""
    import jax

    step = session.step_count if step is None else int(step)
    params_item, opt_item = session.export_state()

    def to_host(tree) -> List[np.ndarray]:
        out = []
        for leaf in jax.tree_util.tree_leaves(tree):
            try:
                out.append(np.asarray(leaf))
            except Exception as e:
                raise SnapshotError(
                    f"leaf not process-addressable ({e}); the RAM tier "
                    "needs host-readable state — recovery falls through "
                    "to the persistent tier") from e
        return out

    leaves = {"params": to_host(params_item),
              "opt_state": to_host(opt_item)}
    if jax.tree_util.tree_leaves(session.sync_state):
        leaves["sync_state"] = to_host(session.sync_state)
    meta: Dict[str, Any] = {"step": step}
    try:
        meta["mesh_axes"] = {str(k): int(v)
                             for k, v in dict(session.mesh.shape).items()}
        meta["data_axis_size"] = int(getattr(session, "data_axis_size", 1))
    except Exception:   # sessions without a mesh (tests, stubs)
        pass
    fp = getattr(session, "schedule_fingerprint", None)
    if fp:
        meta["schedule_fingerprint"] = fp
    zb = tuple(getattr(session, "zero1_buckets", ()) or ())
    if zb:
        from autodist_tpu.resilience.elastic import bucket_layout
        meta["zero1_buckets"] = bucket_layout(zb)
    if extra_meta:
        meta.update(extra_meta)
    digest = _tree_digest([leaves[k] for k in sorted(leaves)])
    return RamSnapshot(step=step, leaves=leaves, digest=digest, meta=meta,
                       time=time.time())


def load_snapshot(session, snap: RamSnapshot) -> int:
    """Restore a snapshot into the session (same-mesh path): leaves are
    unflattened against the session's own restore targets, digest
    re-checked first.  Returns the restored step."""
    import jax

    if not snap.verify():
        raise SnapshotError(
            f"snapshot step {snap.step} failed its digest re-check — "
            "refusing to restore corrupted state")
    want_axes = None
    try:
        want_axes = {str(k): int(v)
                     for k, v in dict(session.mesh.shape).items()}
    except Exception:
        pass
    have_axes = snap.meta.get("mesh_axes")
    if want_axes and have_axes and want_axes != have_axes:
        raise SnapshotError(
            f"snapshot was taken on mesh {have_axes} but this session "
            f"runs {want_axes}; RAM/peer snapshots restore same-mesh "
            "only — use the persistent tier (elastic restore) across a "
            "resize")
    params_target, opt_target = session.restore_targets()

    def unflatten(target, ls: List[np.ndarray]):
        treedef = jax.tree_util.tree_structure(target)
        if treedef.num_leaves != len(ls):
            raise SnapshotError(
                f"snapshot leaf count {len(ls)} != target "
                f"{treedef.num_leaves} (program changed since capture)")
        return jax.tree_util.tree_unflatten(treedef, ls)

    params = unflatten(params_target, snap.leaves["params"])
    opt_state = unflatten(opt_target, snap.leaves["opt_state"])
    sync_state = None
    if "sync_state" in snap.leaves and \
            jax.tree_util.tree_leaves(session.sync_state):
        try:
            sync_state = unflatten(session.sync_state,
                                   snap.leaves["sync_state"])
        except SnapshotError as e:
            logging.warning(
                "snapshot sync_state does not match this session (%s); "
                "reinitializing it — resume is approximate on the "
                "compressor path", e)
    session.import_state(params, opt_state, snap.step,
                         sync_state=sync_state)
    return snap.step


# -- serialization (the peer wire format) ------------------------------------

def snapshot_to_bytes(snap: RamSnapshot) -> bytes:
    """One .npz blob: leaves under ``<item>/<index>`` keys plus a
    ``__meta__`` JSON array — self-describing, numpy-only (no pickle on
    the peer wire)."""
    arrays: Dict[str, np.ndarray] = {}
    counts = {}
    for item, ls in snap.leaves.items():
        counts[item] = len(ls)
        for i, a in enumerate(ls):
            arrays[f"{item}/{i}"] = a
    header = {"step": snap.step, "digest": snap.digest, "meta": snap.meta,
              "time": snap.time, "counts": counts}
    arrays["__meta__"] = np.frombuffer(
        json.dumps(header).encode("utf-8"), dtype=np.uint8)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def snapshot_from_bytes(data: bytes) -> RamSnapshot:
    """Inverse of :func:`snapshot_to_bytes`; raises
    :class:`SnapshotError` on a truncated/garbled blob."""
    try:
        with np.load(io.BytesIO(data), allow_pickle=False) as z:
            header = json.loads(bytes(z["__meta__"].tobytes()).decode())
            leaves = {item: [z[f"{item}/{i}"] for i in range(n)]
                      for item, n in header["counts"].items()}
    except Exception as e:
        raise SnapshotError(f"unreadable snapshot blob: {e}") from e
    return RamSnapshot(step=int(header["step"]), leaves=leaves,
                       digest=header.get("digest"),
                       meta=header.get("meta") or {},
                       time=float(header.get("time") or 0.0))


class SnapshotRing:
    """The host-local RAM tier: last ``keep`` snapshots, newest first on
    iteration.  Pure container — capture/restore live above it."""

    def __init__(self, keep: int = 2):
        if keep < 1:
            raise ValueError("keep must be >= 1")
        self._keep = keep
        self._snaps: List[RamSnapshot] = []   # ascending by step

    def add(self, snap: RamSnapshot) -> None:
        self._snaps = [s for s in self._snaps if s.step != snap.step]
        self._snaps.append(snap)
        self._snaps.sort(key=lambda s: s.step)
        del self._snaps[:-self._keep]

    def steps(self) -> List[int]:
        return [s.step for s in self._snaps]

    def get(self, step: int) -> Optional[RamSnapshot]:
        for s in self._snaps:
            if s.step == step:
                return s
        return None

    def latest(self, verify: bool = True) -> Optional[RamSnapshot]:
        """Newest snapshot that passes its digest re-check; a corrupted
        entry is dropped (with a WARN) and the next-newest is tried —
        the in-RAM analog of ``Saver.latest_step`` skipping a damaged
        step dir."""
        for s in reversed(self._snaps):
            if not verify or s.verify():
                return s
            logging.warning(
                "RAM snapshot step %d failed its digest re-check — "
                "dropping it from the ring", s.step)
            self._snaps.remove(s)
        return None

    @property
    def nbytes(self) -> int:
        return sum(s.nbytes for s in self._snaps)

    def clear(self) -> None:
        self._snaps = []

    def __len__(self) -> int:
        return len(self._snaps)


# -- peer mirroring -----------------------------------------------------------

def buddy_of(hosts: Sequence[str], host: str) -> Optional[str]:
    """Ring buddy assignment: host *i* mirrors to host *i+1 mod n* —
    every host's state survives any single-host loss, with exactly one
    extra copy per host.  None when the host is alone or unknown."""
    hosts = list(hosts)
    if host not in hosts or len(hosts) < 2:
        return None
    return hosts[(hosts.index(host) + 1) % len(hosts)]


def _safe(name: str) -> str:
    return name.replace("/", "_").replace(":", "_")


class PeerMirror:
    """Push/fetch serialized snapshots in a mirror directory.

    ``push`` writes ``<dir>/<owner>/snap_step_<N>.npz`` — through
    ``cluster.remote_copy`` (the retry transport) when a cluster and a
    remote buddy address are given, directly otherwise (the CPU-test
    and shared-tmpfs path).  ``fetch`` reads the newest usable snapshot
    for an owner from the LOCAL view of the directory: a replaced host
    fetches its predecessor's state from the survivor that mirrors it.
    """

    def __init__(self, directory: str, cluster=None,
                 buddy: Optional[str] = None, keep: int = 2):
        self._dir = directory
        self._cluster = cluster
        self._buddy = buddy
        self._keep = max(int(keep), 1)

    @property
    def directory(self) -> str:
        return self._dir

    def _owner_dir(self, owner: str) -> str:
        return os.path.join(self._dir, _safe(owner))

    def push(self, snap: RamSnapshot, owner: str) -> str:
        """Mirror one snapshot; returns the (remote) path.  Retention
        (last ``keep``) is enforced on the destination."""
        data = snapshot_to_bytes(snap)
        dest_dir = self._owner_dir(owner)
        dest = os.path.join(dest_dir, f"snap_step_{snap.step}.npz")
        if self._cluster is not None and self._buddy is not None:
            import tempfile

            with tempfile.NamedTemporaryFile(suffix=".npz",
                                             delete=False) as f:
                f.write(data)
                tmp = f.name
            try:
                self._cluster.remote_copy(tmp, dest, self._buddy)
            finally:
                os.unlink(tmp)
        else:
            os.makedirs(dest_dir, exist_ok=True)
            tmp = dest + ".tmp"
            with open(tmp, "wb") as f:
                f.write(data)
            os.replace(tmp, dest)   # atomic: fetch never sees half a blob
        self._gc(owner)
        return dest

    def _gc(self, owner: str) -> None:
        """Drop mirrored snapshots beyond the ring depth (local view;
        remote buddies GC their own local view on their next push)."""
        steps = self.steps(owner)
        for step in steps[:-self._keep]:
            try:
                os.unlink(os.path.join(self._owner_dir(owner),
                                       f"snap_step_{step}.npz"))
            except OSError:
                pass

    def steps(self, owner: str) -> List[int]:
        try:
            names = os.listdir(self._owner_dir(owner))
        except OSError:
            return []
        return sorted(int(m.group(1)) for n in names
                      if (m := SNAP_RE.match(n)))

    def owners(self) -> List[str]:
        try:
            return sorted(n for n in os.listdir(self._dir)
                          if os.path.isdir(os.path.join(self._dir, n)))
        except OSError:
            return []

    def fetch(self, owner: str, step: Optional[int] = None
              ) -> Optional[RamSnapshot]:
        """Newest (or exact-step) usable snapshot for ``owner`` from the
        local view; unreadable/corrupt blobs are skipped with a WARN."""
        steps = self.steps(_safe(owner))
        if step is not None:
            steps = [s for s in steps if s == step]
        for s in reversed(steps):
            path = os.path.join(self._owner_dir(_safe(owner)),
                                f"snap_step_{s}.npz")
            try:
                with open(path, "rb") as f:
                    snap = snapshot_from_bytes(f.read())
            except (OSError, SnapshotError) as e:
                logging.warning("peer snapshot %s unreadable (%s) — "
                                "skipping", path, e)
                continue
            if not snap.verify():
                logging.warning("peer snapshot %s failed its digest "
                                "check — skipping", path)
                continue
            return snap
        return None

    def fetch_any(self, step: Optional[int] = None
                  ) -> Optional[RamSnapshot]:
        """Newest usable snapshot across ALL owners — the SPMD case
        where every host's state is identical (replicated params) and a
        rejoining host may take anyone's mirror."""
        best = None
        for owner in self.owners():
            snap = self.fetch(owner, step=step)
            if snap is not None and (best is None or snap.step > best.step):
                best = snap
        return best

    def clear(self, owner: Optional[str] = None) -> None:
        """Delete mirrored snapshots (all owners by default) — drill
        cleanup; the no-litter invariant in bench.py checks this."""
        import shutil

        targets = [owner] if owner else self.owners()
        for o in targets:
            shutil.rmtree(self._owner_dir(_safe(o)), ignore_errors=True)


# -- the tier manager ---------------------------------------------------------

class CheckpointTiers:
    """Orchestrates the RAM + peer tiers around one session.

    ``on_step(step)`` is the training-loop hook (one modulo check when
    idle); ``snapshot()`` forces a capture (the emergency-preemption
    path).  ``host_id`` names this host's mirror subdirectory; the
    buddy address routes pushes over the cluster transport when given.
    """

    def __init__(self, session=None, snapshot_every: int = 0,
                 keep: int = 2, peer_dir: Optional[str] = None,
                 cluster=None, buddy: Optional[str] = None,
                 host_id: Optional[str] = None):
        self._session = session
        self.snapshot_every = int(snapshot_every)
        self.ring = SnapshotRing(keep=max(int(keep), 1))
        self.mirror = (PeerMirror(peer_dir, cluster=cluster, buddy=buddy,
                                  keep=max(int(keep), 1))
                       if peer_dir else None)
        self.host_id = host_id or self._default_host_id()
        self._disabled_reason: Optional[str] = None
        self.last_snapshot_s: Optional[float] = None

    @staticmethod
    def _default_host_id() -> str:
        try:
            import jax
            return f"proc{jax.process_index()}"
        except Exception:
            return f"proc{os.environ.get('AUTODIST_PROCESS_ID', 0)}"

    @classmethod
    def from_env(cls, session=None, checkpoint_dir: Optional[str] = None,
                 cluster=None) -> Optional["CheckpointTiers"]:
        """Build from the ``AUTODIST_SNAPSHOT_*`` env knobs; None when
        the tier is not configured (``AUTODIST_SNAPSHOT_EVERY`` unset)."""
        from autodist_tpu.const import ENV

        every = ENV.AUTODIST_SNAPSHOT_EVERY.val
        if not every:
            return None
        peer_dir = ENV.AUTODIST_SNAPSHOT_DIR.val or (
            os.path.join(checkpoint_dir, "peer_tier")
            if checkpoint_dir else None)
        return cls(session, snapshot_every=every,
                   keep=ENV.AUTODIST_SNAPSHOT_KEEP.val, peer_dir=peer_dir,
                   cluster=cluster, buddy=ENV.AUTODIST_BUDDY.val or None)

    @property
    def enabled(self) -> bool:
        return self._disabled_reason is None

    def on_step(self, step: int,
                extra_meta: Optional[dict] = None) -> Optional[RamSnapshot]:
        if (not self.snapshot_every or step <= 0
                or step % self.snapshot_every
                or self._disabled_reason is not None):
            return None
        return self.snapshot(step, extra_meta=extra_meta)

    def snapshot(self, step: Optional[int] = None,
                 extra_meta: Optional[dict] = None,
                 emergency: bool = False) -> Optional[RamSnapshot]:
        """Capture + ring + mirror.  Never raises into the training
        loop: an addressability failure disables the tier with one WARN
        (persistent recovery still works); transport failures keep the
        RAM copy and warn."""
        if self._session is None:
            raise ValueError("CheckpointTiers has no bound session")
        if self._disabled_reason is not None:
            return None
        from autodist_tpu.resilience.heartbeat import heartbeat_phase
        from autodist_tpu.telemetry import emit_event

        t0 = time.perf_counter()
        try:
            with heartbeat_phase("checkpoint/snapshot"):
                snap = capture_snapshot(self._session, step=step,
                                        extra_meta=extra_meta)
        except SnapshotError as e:
            self._disabled_reason = str(e)
            logging.warning("RAM checkpoint tier disabled: %s", e)
            emit_event("checkpoint/ram_tier_disabled", reason=str(e))
            return None
        self.ring.add(snap)
        mirrored = None
        if self.mirror is not None:
            try:
                mirrored = self.mirror.push(snap, self.host_id)
            except Exception as e:   # transport trouble: RAM copy stands
                logging.warning(
                    "peer mirror push failed for step %d (%s) — the "
                    "RAM-local copy is still held", snap.step, e)
        self.last_snapshot_s = time.perf_counter() - t0
        emit_event("checkpoint/ram_snapshot", step=snap.step,
                   bytes=snap.nbytes, ring_depth=len(self.ring),
                   mirrored=bool(mirrored), emergency=emergency,
                   duration_s=round(self.last_snapshot_s, 6))
        return snap

    def cleanup(self) -> None:
        """Drop this host's RAM ring and its mirrored files — the
        end-of-drill no-litter path."""
        self.ring.clear()
        if self.mirror is not None:
            self.mirror.clear(self.host_id)


# -- restore routing ----------------------------------------------------------

def _peer_candidates(tiers: Optional[CheckpointTiers],
                     peer_dir: Optional[str],
                     host_id: Optional[str]) -> Optional[PeerMirror]:
    if tiers is not None and tiers.mirror is not None:
        return tiers.mirror
    if peer_dir:
        return PeerMirror(peer_dir)
    return None


def route_restore(session, directory: Optional[str] = None,
                  tiers: Optional[CheckpointTiers] = None,
                  peer_dir: Optional[str] = None,
                  host_id: Optional[str] = None,
                  validate_elastic: bool = True
                  ) -> Optional[Tuple[int, str, dict]]:
    """Restore the NEWEST usable state across all tiers.

    Candidates: the RAM-local ring (this process survived), the peer
    mirror directory (this host was replaced; a survivor holds its
    state), and the persistent checkpoint under ``directory``.  Newest
    step wins; on a tie the cheaper tier does.  A candidate that fails
    (digest, mesh mismatch, truncation) falls through to the next —
    recovery never gets WORSE than the persistent tier.  Same-mesh
    snapshots restore directly; a persistent restore across a mesh
    resize runs ``preflight_elastic`` first (``validate_elastic``).

    Returns ``(step, tier, meta)`` — the restored step, the tier it
    came from, and the provenance meta that rode it (``data_state`` for
    the exact mid-epoch data resume) — or None when no tier holds
    anything usable.
    """
    from autodist_tpu.checkpoint.saver import Saver
    from autodist_tpu.telemetry import emit_event

    ram = tiers.ring.latest() if tiers is not None else None
    mirror = _peer_candidates(tiers, peer_dir, host_id)
    peer = None
    if mirror is not None:
        own = host_id or (tiers.host_id if tiers is not None
                          else CheckpointTiers._default_host_id())
        # SPMD consistency rule: every process must resume the SAME
        # step, so the candidate is the newest step visible across ALL
        # owners (a host whose own mirror lags — it died mid-cadence —
        # takes a survivor's snapshot of the newer step), preferring
        # this host's own snapshot AT that step when it exists.
        best = mirror.fetch_any()
        if best is not None:
            peer = mirror.fetch(own, step=best.step) or best
    persistent_step = (Saver.latest_step(directory)
                       if directory else None)

    candidates: List[Tuple[int, str, Any]] = []
    if ram is not None:
        candidates.append((ram.step, TIER_RAM, ram))
    if peer is not None:
        candidates.append((peer.step, TIER_PEER, peer))
    if persistent_step is not None:
        candidates.append((persistent_step, TIER_PERSISTENT, None))
    # newest step first; cheaper tier breaks ties (ram < peer <
    # persistent in cost, and the list above is appended in that order,
    # so a stable sort on -step alone preserves it).
    candidates.sort(key=lambda c: -c[0])

    for step, tier, snap in candidates:
        t0 = time.perf_counter()
        meta: dict = {}
        try:
            if tier == TIER_PERSISTENT:
                path = Saver._step_dir(directory, step)
                meta = Saver.read_meta(path)
                mesh_axes = meta.get("mesh_axes")
                try:
                    want = {str(k): int(v)
                            for k, v in dict(session.mesh.shape).items()}
                except Exception:
                    want = None
                if validate_elastic and mesh_axes and want \
                        and mesh_axes != want:
                    from autodist_tpu.resilience.elastic import \
                        preflight_elastic
                    preflight_elastic(session, meta,
                                      context=f"route_restore:{path}")
                restored = Saver(session).restore(path)
            else:
                restored = load_snapshot(session, snap)
                meta = dict(snap.meta)
        except Exception as e:
            logging.warning(
                "restore routing: %s tier step %s unusable (%s) — "
                "falling through", tier, step, e)
            continue
        emit_event("checkpoint/route_restore", tier=tier, step=restored,
                   duration_s=round(time.perf_counter() - t0, 6),
                   candidates=[[c[0], c[1]] for c in candidates])
        logging.info("restore routing: resumed step %d from the %s tier",
                     restored, tier)
        return restored, tier, meta
    return None
