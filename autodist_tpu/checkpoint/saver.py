"""Checkpointing with single-device interchangeability.

Parity target: reference ``autodist/checkpoint/saver.py:27-133`` — a Saver
whose checkpoints use the ORIGINAL single-node variable names/layout, so a
distributed run's checkpoint restores into a plain single-device program and
vice versa (the reference's strongest tested invariant,
``tests/checkpoint/test_partitionedPS_saver.py``), including partitioned
variables reassembled as one logical tensor (``kernel/partitioner.py:252-347``
via SaveSliceInfo).

TPU-natively this is Orbax: checkpoints are written against the *global*
logical shape of every array regardless of its sharding, so a PartitionedPS
run, an AllReduce run, and a single-device run all produce and accept the
same checkpoint; only the restore-time sharding differs.

Layout of one checkpoint: ``<dir>/step_N/{params, opt_state[, sync_state],
autodist_meta.json}`` — separate Orbax items so the params-only interchange
path never reads optimizer slots (~2x the params' bytes under Adam).
Optimizer slots and per-device synchronizer state (compressor residuals) are
saved so resume is exact.

Resilience integration (docs/resilience.md):

* ``autodist_meta.json`` records provenance — mesh axes, the data-axis
  size, and the ZeRO-1 bucket layout — so :meth:`restore` can reshard a
  flat-sharded optimizer checkpoint across a data-axis resize (elastic
  resume, ``resilience/elastic.py``), plus per-item content checksums
  and whatever the caller passes via ``extra_meta`` (``fit`` stores the
  data-loader position for exact mid-epoch resume).
* :meth:`verify` checks a step dir for truncation/corruption (shallow:
  item presence; deep: checksum comparison); :meth:`latest_step` runs
  the shallow check so a damaged step is skipped, not resumed.
* ``keep=N`` garbage-collects old ``step_N`` dirs after a durable save.

Verified-good steps (docs/numerics.md): :meth:`mark_good` stamps a step
that passed :meth:`verify` (deep, by default) AND whose training health
the caller vouches for (``fit`` marks saves taken with a clean numerics
guard).  :meth:`latest_step` prefers verified-good steps over
merely-uncorrupted ones, :meth:`restore_last_good` is the numerics
rollback's restore path, and retention never garbage-collects the last
good step — the rollback anchor survives any ``keep=``.
"""
from __future__ import annotations

import json
import os
import re
import shutil
import time
from typing import Any, Dict, List, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from autodist_tpu.kernel.sharding_utils import abstract_like as _abstract_like
from autodist_tpu.utils import logging

_STEP_RE = re.compile(r"^step_(\d+)$")

#: marker file a verified-good step carries (see Saver.mark_good).
GOOD_MARKER = "VERIFIED_GOOD.json"

#: autodist_meta schema version (1 = step/has_sync_state only).
META_FORMAT = 2

# -- chaos seams (resilience/chaos.py) ---------------------------------------
# ``storage_stall`` injects slow/blocking checkpoint writes; registered
# pre-save hooks fire at the top of every Saver.save (the
# ``kill@...,during=save`` drill arms one that os._exits there, so the
# stranded-partial-save recovery path is exercisable on demand).
_storage_stall_s: float = 0.0
_pre_save_hooks: list = []


def set_storage_stall(seconds: float) -> None:
    """Make every subsequent save/wait sleep ``seconds`` first — the
    deterministic slow-storage drill (0 clears it)."""
    global _storage_stall_s
    _storage_stall_s = max(float(seconds), 0.0)


def add_pre_save_hook(fn) -> None:
    """Register ``fn(path)`` to run at the top of every save (chaos:
    kill-during-save).  Test/drill seam — not a public extension point."""
    _pre_save_hooks.append(fn)


def clear_save_hooks() -> None:
    global _storage_stall_s
    _storage_stall_s = 0.0
    _pre_save_hooks.clear()


def _maybe_stall(where: str) -> None:
    if _storage_stall_s > 0:
        logging.warning("CHAOS storage_stall: %s blocked %.3fs", where,
                        _storage_stall_s)
        time.sleep(_storage_stall_s)


class Saver:
    """Save/restore a :class:`DistributedSession`'s state.

    Like the reference (which required the Saver be created before the
    distributed session so its SaverDef lands in GraphItem.info), binding
    happens at construction; unlike it, late binding via ``session=`` on
    save/restore is also allowed.
    """

    def __init__(self, session=None, async_save: bool = False,
                 keep: Optional[int] = None, checksum: bool = True):
        """``async_save=True`` overlaps checkpoint persistence with
        training: the device→host snapshot is synchronous (so saved values
        are consistent even though the training loop immediately
        donates/overwrites the live buffers) while ALL items persist in
        one background commit.  ``wait()`` — or the next save/restore
        through this Saver — blocks until the previous save is durable.

        ``keep=N`` retains only the N newest committed steps: older
        ``step_M`` dirs are deleted once a newer save is durable (chief
        process only).  ``checksum=False`` skips the per-item content
        digests (they cost one extra device→host pass per item; digests
        are also skipped automatically when shards are not all
        process-addressable).

        Every checkpoint is ONE composite Orbax save (params + opt_state
        [+ sync_state] + meta), committed atomically: a crash mid-save
        leaves no half-checkpoint for :meth:`latest_step` to pick up."""
        if keep is not None and keep < 1:
            raise ValueError("keep must be >= 1 (or None to retain all)")
        self._session = session
        self._async = async_save
        self._keep = keep
        self._checksum = checksum
        self._gc_dir: Optional[str] = None
        self._pending_mark: Optional[str] = None
        self._ckptr = ocp.AsyncCheckpointer(ocp.CompositeCheckpointHandler())
        #: wall seconds the last PERSISTENT save took to become durable
        #: (sync saves: the whole save; async: measured at the next
        #: wait/save boundary) — what the preemption deadline decision
        #: compares against AUTODIST_PREEMPT_GRACE_S.
        self.last_persist_s: Optional[float] = None
        self._async_t0: Optional[float] = None

    def wait(self) -> None:
        """Block until any in-flight async save is durable on disk, then
        apply any deferred good-mark and retention.  The wait is
        phase-tagged on the heartbeat beacon: a long storage stall here
        must read as a checkpoint wait, not a wedge."""
        from autodist_tpu.resilience.heartbeat import heartbeat_phase

        with heartbeat_phase("checkpoint/wait"):
            _maybe_stall("Saver.wait")
            self._ckptr.wait_until_finished()
        if self._async_t0 is not None:
            self.last_persist_s = time.perf_counter() - self._async_t0
            self._async_t0 = None
        self._apply_pending_mark()
        self._maybe_gc()

    def _apply_pending_mark(self) -> None:
        """Good-marking an ASYNC save must wait for durability (a deep
        verify of an in-flight save would fail); applied here once the
        commit is known finished."""
        if self._pending_mark is not None:
            path, self._pending_mark = self._pending_mark, None
            Saver.mark_good(path)

    # -- helpers -----------------------------------------------------------
    @staticmethod
    def _step_dir(directory: str, step: int) -> str:
        return os.path.join(directory, f"step_{step}")

    @staticmethod
    def _committed_steps(directory: str) -> List[int]:
        """Steps whose composite save committed (the whole save lands in
        one atomic Orbax commit, so an interrupted async save leaves
        step_N without the final ``params`` item)."""
        if not os.path.isdir(directory):
            return []
        steps = []
        for name in os.listdir(directory):
            m = _STEP_RE.match(name)
            if not m:
                continue
            if os.path.isdir(os.path.join(directory, name, "params")):
                steps.append(int(m.group(1)))
        return sorted(steps)

    @staticmethod
    def good_steps(directory: str) -> List[int]:
        """Committed steps carrying a :meth:`mark_good` marker, sorted."""
        return [s for s in Saver._committed_steps(directory)
                if os.path.exists(os.path.join(
                    Saver._step_dir(directory, s), GOOD_MARKER))]

    @staticmethod
    def latest_step(directory: str, verify: bool = True) -> Optional[int]:
        """Newest usable step, VERIFIED-GOOD steps first: a step that
        passed :meth:`mark_good` (deep verify + healthy training state)
        outranks a newer merely-uncorrupted one — resuming onto a
        poisoned-but-intact checkpoint is the failure mode the numerics
        guard exists to prevent.  Within each class, newest first; every
        candidate still passes the shallow :meth:`verify` (a corrupt or
        truncated step is skipped with a warning).  Directories with no
        good markers behave exactly as before."""
        committed = Saver._committed_steps(directory)
        good = set(Saver.good_steps(directory))
        ranked = [s for s in reversed(committed) if s in good] \
            + [s for s in reversed(committed) if s not in good]
        for step in ranked:
            path = Saver._step_dir(directory, step)
            if not verify or Saver.verify(path):
                return step
            logging.warning(
                "checkpoint %s failed verification — skipping it for "
                "resume", path)
        return None

    @staticmethod
    def latest_checkpoint(directory: str) -> Optional[str]:
        step = Saver.latest_step(directory)
        return None if step is None else Saver._step_dir(directory, step)

    # -- metadata ----------------------------------------------------------
    @staticmethod
    def _read_meta_strict(path: str) -> dict:
        """The composite ``autodist_meta`` item; raises when it exists but
        cannot be parsed (corruption — verify turns that into a skip)."""
        with ocp.Checkpointer(ocp.CompositeCheckpointHandler()) as ckptr:
            restored = ckptr.restore(
                os.path.abspath(path),
                args=ocp.args.Composite(autodist_meta=ocp.args.JsonRestore()))
        return dict(restored["autodist_meta"])

    @staticmethod
    def read_meta(path: str) -> dict:
        """Best-effort checkpoint metadata: the composite item, a legacy
        plain ``autodist_meta.json``, or a filename-derived step."""
        try:
            return Saver._read_meta_strict(path)
        except Exception:
            return _read_meta(path)

    # -- integrity ---------------------------------------------------------
    @staticmethod
    def verify(path: str, deep: bool = False) -> bool:
        """Is this step dir a usable checkpoint?

        Shallow (default): every item recorded in the meta exists as a
        non-empty directory and the meta itself parses — catches
        interrupted/partially deleted saves.  ``deep=True`` additionally
        restores each checksummed item to host and compares content
        digests — catches byte-level truncation/corruption inside item
        files.  Never raises."""
        path = os.path.abspath(path)
        if not os.path.isdir(path):
            return False
        if not _nonempty_dir(os.path.join(path, "params")):
            return False
        meta_present = os.path.isdir(os.path.join(path, "autodist_meta")) \
            or os.path.exists(os.path.join(path, "autodist_meta.json"))
        meta: dict = {}
        if meta_present:
            try:
                meta = Saver._read_meta_strict(path)
            except Exception:
                try:
                    meta = _read_meta(path)
                except Exception:
                    return False
                if not meta:
                    return False
        for item in meta.get("items", []):
            if item == "autodist_meta":
                continue
            if not _nonempty_dir(os.path.join(path, item)):
                logging.warning("checkpoint %s: item %s missing/empty",
                                path, item)
                return False
        if deep:
            sums = meta.get("checksums") or {}
            for item, want in sums.items():
                if want is None:
                    continue
                try:
                    got = _tree_digest(_restore_item_host(path, item))
                except Exception as e:
                    logging.warning("checkpoint %s: item %s unreadable "
                                    "(%s)", path, item, e)
                    return False
                if got != want:
                    logging.warning(
                        "checkpoint %s: item %s checksum mismatch "
                        "(%s != %s)", path, item, got, want)
                    return False
        return True

    # -- verified-good steps (docs/numerics.md) ----------------------------
    @staticmethod
    def mark_good(path: str, deep: bool = True) -> bool:
        """Stamp a step dir as *verified-good*: it passes :meth:`verify`
        (``deep=True`` re-reads every checksummed item — the PR 4
        integrity machinery) and the caller vouches for the training
        state it froze (``fit`` only marks saves taken with a clean
        numerics guard).  Returns False — without stamping — when
        verification fails.  The marker makes the step preferred by
        :meth:`latest_step`, restorable by :meth:`restore_last_good`,
        and immune to ``keep=`` garbage collection (last one)."""
        path = os.path.abspath(path)
        t_verify = time.perf_counter()
        ok = Saver.verify(path, deep=deep)
        from autodist_tpu.telemetry import emit_event
        emit_event("checkpoint/verify", path=path, deep=deep, ok=ok,
                   duration_s=round(time.perf_counter() - t_verify, 6))
        if not ok:
            logging.warning(
                "mark_good: %s failed %s verification — NOT marked",
                path, "deep" if deep else "shallow")
            return False
        meta = Saver.read_meta(path)
        marker = os.path.join(path, GOOD_MARKER)
        tmp = marker + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"step": int(meta.get("step", 0)),
                       "deep_verified": bool(deep),
                       "time": time.time()}, f)
        os.replace(tmp, marker)
        logging.info("checkpoint %s marked verified-good", path)
        return True

    @staticmethod
    def last_good_checkpoint(directory: str) -> Optional[str]:
        """Newest verified-good step dir that still passes verification
        (shallow here; the marker already attests a deep pass), or None."""
        for step in reversed(Saver.good_steps(directory)):
            path = Saver._step_dir(directory, step)
            if Saver.verify(path):
                return path
            logging.warning(
                "checkpoint %s was marked good but no longer verifies — "
                "skipping", path)
        return None

    def restore_last_good(self, directory: str, session=None) -> int:
        """Restore the newest verified-good checkpoint (the numerics
        rollback path); returns its step.  Raises FileNotFoundError when
        no good step exists — the caller decides whether that is fatal
        (``fit`` raises NonFiniteError)."""
        path = self.last_good_checkpoint(directory)
        if path is None:
            raise FileNotFoundError(
                f"no verified-good checkpoint under {directory} "
                "(mark_good was never called, or every good step was "
                "corrupted)")
        return self.restore(path, session=session)

    # -- retention ---------------------------------------------------------
    def _maybe_gc(self) -> None:
        if self._keep is None or self._gc_dir is None:
            return
        try:
            if jax.process_count() > 1 and jax.process_index() != 0:
                return   # one process owns the shared directory
        except Exception:
            pass
        steps = self._committed_steps(self._gc_dir)
        good = self.good_steps(self._gc_dir)
        protected = {max(good)} if good else set()
        for step in steps[:-self._keep]:
            if step in protected:
                # The last verified-good step is the rollback anchor:
                # keep= must never delete it, or a numerics rollback
                # would have nothing safe to restore.
                logging.info(
                    "checkpoint retention (keep=%d): keeping verified-"
                    "good step_%d beyond the window", self._keep, step)
                continue
            victim = self._step_dir(self._gc_dir, step)
            shutil.rmtree(victim, ignore_errors=True)
            logging.info("checkpoint retention (keep=%d): removed %s",
                         self._keep, victim)

    # -- save --------------------------------------------------------------
    def save(self, directory: str, step: Optional[int] = None,
             session=None, extra_meta: Optional[dict] = None,
             mark_good: bool = False) -> str:
        """``mark_good=True`` additionally stamps the step verified-good
        once durable (immediately for sync saves; at the next
        :meth:`wait`/save boundary for async ones) — the caller's
        attestation that the saved training state is healthy (``fit``
        sets it from the numerics guard)."""
        session = session or self._session
        if session is None:
            raise ValueError("Saver has no bound session")
        t_save = time.perf_counter()
        step = session.step_count if step is None else step
        path = self._step_dir(directory, step)
        for hook in list(_pre_save_hooks):   # chaos: kill-during-save
            hook(path)
        from autodist_tpu.resilience.heartbeat import heartbeat_phase
        with heartbeat_phase("checkpoint/save"):
            _maybe_stall("Saver.save")
            self._ckptr.wait_until_finished()  # one async save in flight max
        if self._async_t0 is not None:      # the PREVIOUS async save
            self.last_persist_s = time.perf_counter() - self._async_t0
            self._async_t0 = None
        self._apply_pending_mark()
        self._maybe_gc()                    # previous save is durable now
        # LOGICAL layout (pad-to-divisible sharding stripped): checkpoints
        # stay interchangeable with single-device programs and across
        # mesh topologies regardless of physical padding.
        params_item, opt_item = session.export_state()
        has_sync = bool(jax.tree_util.tree_leaves(session.sync_state))
        item_names = ["params", "opt_state", "autodist_meta"] \
            + (["sync_state"] if has_sync else [])
        meta: Dict[str, Any] = {
            "step": step, "has_sync_state": has_sync,
            "format": META_FORMAT, "items": item_names,
        }
        try:
            meta["mesh_axes"] = {str(k): int(v)
                                 for k, v in dict(session.mesh.shape).items()}
            meta["data_axis_size"] = int(getattr(session, "data_axis_size",
                                                 1))
        except Exception:   # sessions without a mesh (tests, stubs)
            pass
        zb = tuple(getattr(session, "zero1_buckets", ()) or ())
        if zb:
            # The flat-sharded optimizer layout: what elastic resume needs
            # to reshard this checkpoint at a different data-axis size.
            from autodist_tpu.resilience.elastic import bucket_layout
            meta["zero1_buckets"] = bucket_layout(zb)
        # Sync-schedule provenance (docs/schedule-ir.md): the fingerprint
        # of the schedule this checkpoint's session executed, so a resume
        # (same mesh) can detect planned-vs-executed schedule drift and an
        # elastic resize re-verifies against the recorded plan.
        fp = getattr(session, "schedule_fingerprint", None)
        if fp:
            meta["schedule_fingerprint"] = fp
        if self._checksum:
            sums = {"params": _tree_digest(params_item),
                    "opt_state": _tree_digest(opt_item)}
            if has_sync:
                sums["sync_state"] = _tree_digest(session.sync_state)
            meta["checksums"] = {k: v for k, v in sums.items()
                                 if v is not None}
        if extra_meta:
            meta.update(extra_meta)
        items = dict(
            params=ocp.args.StandardSave(params_item),
            opt_state=ocp.args.StandardSave(opt_item),
            autodist_meta=ocp.args.JsonSave(meta),
        )
        if has_sync:
            items["sync_state"] = ocp.args.StandardSave(session.sync_state)
        with heartbeat_phase("checkpoint/save"):
            self._ckptr.save(os.path.abspath(path),
                             args=ocp.args.Composite(**items), force=True)
            self._gc_dir = directory
            if mark_good:
                self._pending_mark = path
            if not self._async:
                self._ckptr.wait_until_finished()
                self.last_persist_s = time.perf_counter() - t_save
                self._apply_pending_mark()
                self._maybe_gc()
            else:
                self._async_t0 = t_save
        logging.info("checkpoint %s: %s (step %d)",
                     "saving in background" if self._async else "saved",
                     path, step)
        # Journal the save (docs/observability.md).  For async saves the
        # duration covers snapshot + dispatch; durability lands at the
        # next wait()/save boundary.
        from autodist_tpu.telemetry import emit_event
        emit_event("checkpoint/save", step=int(step), path=path,
                   duration_s=round(time.perf_counter() - t_save, 6),
                   async_save=self._async, mark_good=mark_good)
        return path

    # -- restore -----------------------------------------------------------
    def restore(self, path: str, session=None) -> int:
        """Restore params + optimizer state (+ synchronizer state) into the
        (possibly differently sharded) session; returns the step.

        When the checkpoint's ZeRO-1 bucket layout was written at a
        different data-axis size, the flat optimizer shards are resliced
        for this session's axis (elastic resume — exact on the
        params/opt path; see ``resilience/elastic.py``)."""
        session = session or self._session
        if session is None:
            raise ValueError("Saver has no bound session")
        t_restore = time.perf_counter()
        from autodist_tpu.resilience.heartbeat import heartbeat_phase
        with heartbeat_phase("checkpoint/restore"):
            return self._restore_inner(path, session, t_restore)

    def _restore_inner(self, path: str, session, t_restore: float) -> int:
        self._ckptr.wait_until_finished()   # don't read an in-flight save
        self._apply_pending_mark()
        path = os.path.abspath(path)
        meta = self.read_meta(path)
        params_target, opt_target = session.restore_targets()

        elastic = None
        old_layout = meta.get("zero1_buckets") or []
        new_buckets = tuple(getattr(session, "zero1_buckets", ()) or ())
        if old_layout and new_buckets:
            from autodist_tpu.resilience import elastic as elastic_mod
            mismatch = elastic_mod.layout_mismatch(old_layout, new_buckets)
            if mismatch:
                raise elastic_mod.ElasticResumeError(
                    f"cannot resume {path}: {mismatch}; elastic resume "
                    "requires the same bucket membership (same "
                    "bucket_bytes / variable catalog) at any axis size")
            if elastic_mod.needs_reshard(old_layout, new_buckets):
                elastic = elastic_mod
                opt_target = elastic_mod.old_shaped_opt_target(
                    opt_target, old_layout, new_buckets, session.mesh)

        restored = self._ckptr.restore(path, args=ocp.args.Composite(
            params=ocp.args.StandardRestore(params_target),
            opt_state=ocp.args.StandardRestore(opt_target)))
        params, opt_state = restored["params"], restored["opt_state"]
        if elastic is not None:
            opt_state = elastic.reshard_opt_state(opt_state, old_layout,
                                                  session)
            logging.info(
                "elastic resume: resliced %d ZeRO-1 optimizer bucket(s) "
                "from data-axis %s to %s (exact — only zero padding "
                "changed)", len(old_layout),
                meta.get("data_axis_size", "?"),
                getattr(session, "data_axis_size", "?"))
            from autodist_tpu.telemetry import emit_event
            emit_event("elastic/reshard", path=path,
                       buckets=len(old_layout),
                       from_axis=meta.get("data_axis_size"),
                       to_axis=getattr(session, "data_axis_size", None))
        sync_state = None
        if meta.get("has_sync_state") and \
                jax.tree_util.tree_leaves(session.sync_state):
            # sync_state (proxy mirrors, delay queues, residuals) is saved in
            # the step's PHYSICAL layout, which is mesh-dependent when
            # pad-to-divisible sharding is active — a cross-topology restore
            # can shape-mismatch.  Fall back to reinitialization (resume is
            # then approximate, as documented on load_state) rather than
            # failing the params/opt restore that IS topology-portable.
            try:
                sync_state = self._ckptr.restore(
                    path, args=ocp.args.Composite(
                        sync_state=ocp.args.StandardRestore(
                            _abstract_like(session.sync_state))))["sync_state"]
            except Exception as e:
                logging.warning(
                    "sync_state in %s does not match this session's layout "
                    "(%s); reinitializing it — resume is approximate", path, e)
        step = int(meta.get("step", 0))
        session.import_state(params, opt_state, step, sync_state=sync_state)
        # Schedule drift: a resume on the SAME mesh should execute the
        # schedule the checkpoint was trained under; a differing
        # fingerprint means bucketing/overlap/guard config drifted (an
        # elastic resize legitimately changes it — hop counts scale with
        # the axis — and is reported at INFO by the analysis pass).
        old_fp = meta.get("schedule_fingerprint")
        new_fp = getattr(session, "schedule_fingerprint", None)
        if old_fp and new_fp and old_fp != new_fp:
            same_mesh = (meta.get("mesh_axes") or {}) == {
                str(k): int(v)
                for k, v in dict(session.mesh.shape).items()} \
                if meta.get("mesh_axes") else False
            (logging.warning if same_mesh else logging.info)(
                "checkpoint %s was written under sync schedule %s but "
                "this session executes %s%s", path, old_fp, new_fp,
                " — same mesh, so the sync config itself drifted "
                "(bucket_bytes/overlap/compressor/guard)" if same_mesh
                else " (expected across an elastic mesh resize)")
        logging.info("checkpoint restored: %s (step %d)", path, step)
        from autodist_tpu.telemetry import emit_event
        emit_event("checkpoint/restore", step=step, path=path,
                   duration_s=round(time.perf_counter() - t_restore, 6),
                   elastic=elastic is not None)
        return step

    @staticmethod
    def restore_params(path: str) -> Any:
        """Restore ONLY parameters as host numpy arrays in the original
        single-device layout — the interchange path: a plain JAX program can
        consume the result of any distributed run, on ANY topology (a
        single TPU chip can read a checkpoint written by a 64-chip mesh).
        Reads only the params item, never the optimizer slots."""
        return _restore_item_host(path, "params")


def save_params(path: str, params: Any) -> str:
    """Module-level utility: save a bare params pytree (e.g. from a
    single-device run) in the same layout Saver produces, so distributed
    sessions can ``restore_params`` it."""
    path = os.path.abspath(path)
    os.makedirs(path, exist_ok=True)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.join(path, "params"), params, force=True)
    ckptr.wait_until_finished()
    return path


def _nonempty_dir(path: str) -> bool:
    try:
        with os.scandir(path) as it:
            return any(True for _ in it)
    except OSError:
        return False


def _restore_item_host(path: str, item: str) -> Any:
    """One checkpoint item as host numpy arrays, with no target tree.

    Restoring without a target replays the original device topology,
    which breaks across machines; build a single-device target from the
    item's own shape/dtype metadata instead.  Modern orbax wraps the
    tree in ``.item_metadata``; older versions return it directly."""
    path = os.path.abspath(os.path.join(path, item))
    ckptr = ocp.StandardCheckpointer()
    meta = ckptr.metadata(path)
    meta = getattr(meta, "item_metadata", meta)
    meta = getattr(meta, "tree", meta)
    dev = jax.local_devices()[0]
    sharding = jax.sharding.SingleDeviceSharding(dev)
    abstract = jax.tree_util.tree_map(
        lambda m: jax.ShapeDtypeStruct(tuple(m.shape), m.dtype,
                                       sharding=sharding), meta)
    tree = ckptr.restore(path, abstract)
    return jax.tree_util.tree_map(np.asarray, tree)


def _tree_digest(tree: Any) -> Optional[str]:
    """Content digest of a pytree: per-leaf CRC32 over (shape, dtype,
    bytes), combined ORDER- and STRUCTURE-independently (sum mod 2^64).

    Structure independence matters because the save-side tree (optax
    NamedTuples, custom nodes) and the verify-side tree (orbax's
    metadata-restored plain containers) flatten with different key paths;
    content equality is what corruption detection needs.  Returns None
    when leaves are not process-addressable (multi-host shards) — the
    digest is then skipped, never wrong."""
    import zlib

    total = 0
    try:
        for leaf in jax.tree_util.tree_leaves(tree):
            arr = np.asarray(leaf)
            head = f"{arr.shape}|{arr.dtype}".encode()
            crc = zlib.crc32(np.ascontiguousarray(arr).tobytes(),
                             zlib.crc32(head))
            total = (total + crc) & 0xFFFFFFFFFFFFFFFF
    except Exception as e:
        logging.debug("checkpoint digest skipped: %s", e)
        return None
    return f"{total:016x}"


def _read_meta(path: str) -> dict:
    meta = os.path.join(path, "autodist_meta.json")
    if os.path.exists(meta):
        with open(meta, "r", encoding="utf-8") as f:
            return json.load(f)
    m = _STEP_RE.match(os.path.basename(path))
    return {"step": int(m.group(1)) if m else 0}
