"""Checkpointing with single-device interchangeability.

Parity target: reference ``autodist/checkpoint/saver.py:27-133`` — a Saver
whose checkpoints use the ORIGINAL single-node variable names/layout, so a
distributed run's checkpoint restores into a plain single-device program and
vice versa (the reference's strongest tested invariant,
``tests/checkpoint/test_partitionedPS_saver.py``), including partitioned
variables reassembled as one logical tensor (``kernel/partitioner.py:252-347``
via SaveSliceInfo).

TPU-natively this is Orbax: checkpoints are written against the *global*
logical shape of every array regardless of its sharding, so a PartitionedPS
run, an AllReduce run, and a single-device run all produce and accept the
same checkpoint; only the restore-time sharding differs.

Layout of one checkpoint: ``<dir>/step_N/{params, opt_state[, sync_state],
autodist_meta.json}`` — separate Orbax items so the params-only interchange
path never reads optimizer slots (~2x the params' bytes under Adam).
Optimizer slots and per-device synchronizer state (compressor residuals) are
saved so resume is exact.
"""
from __future__ import annotations

import json
import os
import re
from typing import Any, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from autodist_tpu.kernel.sharding_utils import abstract_like as _abstract_like
from autodist_tpu.utils import logging

_STEP_RE = re.compile(r"^step_(\d+)$")


class Saver:
    """Save/restore a :class:`DistributedSession`'s state.

    Like the reference (which required the Saver be created before the
    distributed session so its SaverDef lands in GraphItem.info), binding
    happens at construction; unlike it, late binding via ``session=`` on
    save/restore is also allowed.
    """

    def __init__(self, session=None, async_save: bool = False):
        """``async_save=True`` overlaps checkpoint persistence with
        training: the device→host snapshot is synchronous (so saved values
        are consistent even though the training loop immediately
        donates/overwrites the live buffers) while ALL items persist in
        one background commit.  ``wait()`` — or the next save/restore
        through this Saver — blocks until the previous save is durable.

        Every checkpoint is ONE composite Orbax save (params + opt_state
        [+ sync_state] + meta), committed atomically: a crash mid-save
        leaves no half-checkpoint for :meth:`latest_step` to pick up."""
        self._session = session
        self._async = async_save
        self._ckptr = ocp.AsyncCheckpointer(ocp.CompositeCheckpointHandler())

    def wait(self) -> None:
        """Block until any in-flight async save is durable on disk."""
        self._ckptr.wait_until_finished()

    # -- helpers -----------------------------------------------------------
    @staticmethod
    def _step_dir(directory: str, step: int) -> str:
        return os.path.join(directory, f"step_{step}")

    @staticmethod
    def latest_step(directory: str) -> Optional[int]:
        if not os.path.isdir(directory):
            return None
        steps = []
        for name in os.listdir(directory):
            m = _STEP_RE.match(name)
            if not m:
                continue
            # Only committed checkpoints count: the whole composite save
            # (params + opt_state + meta) lands in one atomic Orbax
            # commit, so an interrupted async save leaves step_N without
            # the final `params` item — resume falls back to the previous
            # complete step.
            if os.path.isdir(os.path.join(directory, name, "params")):
                steps.append(int(m.group(1)))
        return max(steps) if steps else None

    @staticmethod
    def latest_checkpoint(directory: str) -> Optional[str]:
        step = Saver.latest_step(directory)
        return None if step is None else Saver._step_dir(directory, step)

    # -- save --------------------------------------------------------------
    def save(self, directory: str, step: Optional[int] = None,
             session=None) -> str:
        session = session or self._session
        if session is None:
            raise ValueError("Saver has no bound session")
        self._ckptr.wait_until_finished()   # one async save in flight max
        step = session.step_count if step is None else step
        path = self._step_dir(directory, step)
        # LOGICAL layout (pad-to-divisible sharding stripped): checkpoints
        # stay interchangeable with single-device programs and across
        # mesh topologies regardless of physical padding.
        params_item, opt_item = session.export_state()
        has_sync = bool(jax.tree_util.tree_leaves(session.sync_state))
        items = dict(
            params=ocp.args.StandardSave(params_item),
            opt_state=ocp.args.StandardSave(opt_item),
            autodist_meta=ocp.args.JsonSave(
                {"step": step, "has_sync_state": has_sync}),
        )
        if has_sync:
            items["sync_state"] = ocp.args.StandardSave(session.sync_state)
        self._ckptr.save(os.path.abspath(path),
                         args=ocp.args.Composite(**items), force=True)
        if not self._async:
            self._ckptr.wait_until_finished()
        logging.info("checkpoint %s: %s (step %d)",
                     "saving in background" if self._async else "saved",
                     path, step)
        return path

    # -- restore -----------------------------------------------------------
    def restore(self, path: str, session=None) -> int:
        """Restore params + optimizer state (+ synchronizer state) into the
        (possibly differently sharded) session; returns the step."""
        session = session or self._session
        if session is None:
            raise ValueError("Saver has no bound session")
        self._ckptr.wait_until_finished()   # don't read an in-flight save
        path = os.path.abspath(path)
        params_target, opt_target = session.restore_targets()
        restored = self._ckptr.restore(path, args=ocp.args.Composite(
            params=ocp.args.StandardRestore(params_target),
            opt_state=ocp.args.StandardRestore(opt_target)))
        params, opt_state = restored["params"], restored["opt_state"]
        try:
            meta = self._ckptr.restore(path, args=ocp.args.Composite(
                autodist_meta=ocp.args.JsonRestore()))["autodist_meta"]
        except Exception:
            meta = None   # pre-composite checkpoint: meta is a plain file
        meta = meta or _read_meta(path)
        sync_state = None
        if meta.get("has_sync_state") and \
                jax.tree_util.tree_leaves(session.sync_state):
            # sync_state (proxy mirrors, delay queues, residuals) is saved in
            # the step's PHYSICAL layout, which is mesh-dependent when
            # pad-to-divisible sharding is active — a cross-topology restore
            # can shape-mismatch.  Fall back to reinitialization (resume is
            # then approximate, as documented on load_state) rather than
            # failing the params/opt restore that IS topology-portable.
            try:
                sync_state = self._ckptr.restore(
                    path, args=ocp.args.Composite(
                        sync_state=ocp.args.StandardRestore(
                            _abstract_like(session.sync_state))))["sync_state"]
            except Exception as e:
                logging.warning(
                    "sync_state in %s does not match this session's layout "
                    "(%s); reinitializing it — resume is approximate", path, e)
        step = int(meta.get("step", 0))
        session.import_state(params, opt_state, step, sync_state=sync_state)
        logging.info("checkpoint restored: %s (step %d)", path, step)
        return step

    @staticmethod
    def restore_params(path: str) -> Any:
        """Restore ONLY parameters as host numpy arrays in the original
        single-device layout — the interchange path: a plain JAX program can
        consume the result of any distributed run, on ANY topology (a
        single TPU chip can read a checkpoint written by a 64-chip mesh).
        Reads only the params item, never the optimizer slots."""
        path = os.path.abspath(os.path.join(path, "params"))
        ckptr = ocp.StandardCheckpointer()
        # Restoring without a target replays the original device topology,
        # which breaks across machines; build a replicated-on-current-devices
        # target from the checkpoint's own shape/dtype metadata instead.
        # Modern orbax wraps the tree in .item_metadata; older versions
        # return the metadata tree directly.
        meta = ckptr.metadata(path)
        meta = getattr(meta, "item_metadata", meta)
        meta = getattr(meta, "tree", meta)
        dev = jax.local_devices()[0]
        sharding = jax.sharding.SingleDeviceSharding(dev)
        abstract = jax.tree_util.tree_map(
            lambda m: jax.ShapeDtypeStruct(tuple(m.shape), m.dtype,
                                           sharding=sharding), meta)
        params = ckptr.restore(path, abstract)
        return jax.tree_util.tree_map(np.asarray, params)


def save_params(path: str, params: Any) -> str:
    """Module-level utility: save a bare params pytree (e.g. from a
    single-device run) in the same layout Saver produces, so distributed
    sessions can ``restore_params`` it."""
    path = os.path.abspath(path)
    os.makedirs(path, exist_ok=True)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.join(path, "params"), params, force=True)
    ckptr.wait_until_finished()
    return path




def _read_meta(path: str) -> dict:
    meta = os.path.join(path, "autodist_meta.json")
    if os.path.exists(meta):
        with open(meta, "r", encoding="utf-8") as f:
            return json.load(f)
    m = _STEP_RE.match(os.path.basename(path))
    return {"step": int(m.group(1)) if m else 0}
