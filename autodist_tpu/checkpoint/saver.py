"""Checkpointing with single-device interchangeability.

Parity target: reference ``autodist/checkpoint/saver.py:27-133`` — a Saver
whose checkpoints use the ORIGINAL single-node variable names/layout, so a
distributed run's checkpoint restores into a plain single-device program and
vice versa (the reference's strongest tested invariant,
``tests/checkpoint/test_partitionedPS_saver.py``), including partitioned
variables reassembled as one logical tensor (``kernel/partitioner.py:252-347``
via SaveSliceInfo).

TPU-natively this is Orbax: checkpoints are written against the *global*
logical shape of every array regardless of its sharding, so a PartitionedPS
run, an AllReduce run, and a single-device run all produce and accept the
same checkpoint; only the restore-time sharding differs.

Layout of one checkpoint: ``<dir>/step_N/{params, opt_state[, sync_state],
autodist_meta.json}`` — separate Orbax items so the params-only interchange
path never reads optimizer slots (~2x the params' bytes under Adam).
Optimizer slots and per-device synchronizer state (compressor residuals) are
saved so resume is exact.
"""
from __future__ import annotations

import json
import os
import re
from typing import Any, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from autodist_tpu.kernel.sharding_utils import abstract_like as _abstract_like
from autodist_tpu.utils import logging

_STEP_RE = re.compile(r"^step_(\d+)$")


class Saver:
    """Save/restore a :class:`DistributedSession`'s state.

    Like the reference (which required the Saver be created before the
    distributed session so its SaverDef lands in GraphItem.info), binding
    happens at construction; unlike it, late binding via ``session=`` on
    save/restore is also allowed.
    """

    def __init__(self, session=None):
        self._session = session
        self._ckptr = ocp.StandardCheckpointer()

    # -- helpers -----------------------------------------------------------
    @staticmethod
    def _step_dir(directory: str, step: int) -> str:
        return os.path.join(directory, f"step_{step}")

    @staticmethod
    def latest_step(directory: str) -> Optional[int]:
        if not os.path.isdir(directory):
            return None
        steps = [int(m.group(1)) for name in os.listdir(directory)
                 if (m := _STEP_RE.match(name))]
        return max(steps) if steps else None

    @staticmethod
    def latest_checkpoint(directory: str) -> Optional[str]:
        step = Saver.latest_step(directory)
        return None if step is None else Saver._step_dir(directory, step)

    def _save_item(self, path: str, item: Any) -> None:
        self._ckptr.save(os.path.abspath(path), item, force=True)
        self._ckptr.wait_until_finished()

    # -- save --------------------------------------------------------------
    def save(self, directory: str, step: Optional[int] = None,
             session=None) -> str:
        session = session or self._session
        if session is None:
            raise ValueError("Saver has no bound session")
        step = session.step_count if step is None else step
        path = self._step_dir(directory, step)
        os.makedirs(path, exist_ok=True)
        # LOGICAL layout (pad-to-divisible sharding stripped): checkpoints
        # stay interchangeable with single-device programs and across
        # mesh topologies regardless of physical padding.
        params_item, opt_item = session.export_state()
        self._save_item(os.path.join(path, "params"), params_item)
        self._save_item(os.path.join(path, "opt_state"), opt_item)
        has_sync = bool(jax.tree_util.tree_leaves(session.sync_state))
        if has_sync:
            self._save_item(os.path.join(path, "sync_state"),
                            session.sync_state)
        with open(os.path.join(path, "autodist_meta.json"), "w",
                  encoding="utf-8") as f:
            json.dump({"step": step, "has_sync_state": has_sync}, f)
        logging.info("checkpoint saved: %s (step %d)", path, step)
        return path

    # -- restore -----------------------------------------------------------
    def restore(self, path: str, session=None) -> int:
        """Restore params + optimizer state (+ synchronizer state) into the
        (possibly differently sharded) session; returns the step."""
        session = session or self._session
        if session is None:
            raise ValueError("Saver has no bound session")
        path = os.path.abspath(path)
        params_target, opt_target = session.restore_targets()
        params = self._ckptr.restore(os.path.join(path, "params"),
                                     params_target)
        opt_state = self._ckptr.restore(os.path.join(path, "opt_state"),
                                        opt_target)
        meta = _read_meta(path)
        sync_state = None
        if meta.get("has_sync_state") and \
                jax.tree_util.tree_leaves(session.sync_state):
            # sync_state (proxy mirrors, delay queues, residuals) is saved in
            # the step's PHYSICAL layout, which is mesh-dependent when
            # pad-to-divisible sharding is active — a cross-topology restore
            # can shape-mismatch.  Fall back to reinitialization (resume is
            # then approximate, as documented on load_state) rather than
            # failing the params/opt restore that IS topology-portable.
            try:
                sync_state = self._ckptr.restore(
                    os.path.join(path, "sync_state"),
                    _abstract_like(session.sync_state))
            except Exception as e:
                logging.warning(
                    "sync_state in %s does not match this session's layout "
                    "(%s); reinitializing it — resume is approximate", path, e)
        step = int(meta.get("step", 0))
        session.import_state(params, opt_state, step, sync_state=sync_state)
        logging.info("checkpoint restored: %s (step %d)", path, step)
        return step

    @staticmethod
    def restore_params(path: str) -> Any:
        """Restore ONLY parameters as host numpy arrays in the original
        single-device layout — the interchange path: a plain JAX program can
        consume the result of any distributed run, on ANY topology (a
        single TPU chip can read a checkpoint written by a 64-chip mesh).
        Reads only the params item, never the optimizer slots."""
        path = os.path.abspath(os.path.join(path, "params"))
        ckptr = ocp.StandardCheckpointer()
        # Restoring without a target replays the original device topology,
        # which breaks across machines; build a replicated-on-current-devices
        # target from the checkpoint's own shape/dtype metadata instead.
        meta = ckptr.metadata(path).item_metadata.tree
        dev = jax.local_devices()[0]
        sharding = jax.sharding.SingleDeviceSharding(dev)
        abstract = jax.tree_util.tree_map(
            lambda m: jax.ShapeDtypeStruct(tuple(m.shape), m.dtype,
                                           sharding=sharding), meta)
        params = ckptr.restore(path, abstract)
        return jax.tree_util.tree_map(np.asarray, params)


def save_params(path: str, params: Any) -> str:
    """Module-level utility: save a bare params pytree (e.g. from a
    single-device run) in the same layout Saver produces, so distributed
    sessions can ``restore_params`` it."""
    path = os.path.abspath(path)
    os.makedirs(path, exist_ok=True)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.join(path, "params"), params, force=True)
    ckptr.wait_until_finished()
    return path




def _read_meta(path: str) -> dict:
    meta = os.path.join(path, "autodist_meta.json")
    if os.path.exists(meta):
        with open(meta, "r", encoding="utf-8") as f:
            return json.load(f)
    m = _STEP_RE.match(os.path.basename(path))
    return {"step": int(m.group(1)) if m else 0}
