"""SavedModel-equivalent export.

Parity target: reference ``autodist/checkpoint/saved_model_builder.py:24-64``
(wraps TF's SavedModelBuilder; requires an AutoDist saver).  The TPU-native
serving artifact is a **StableHLO export** (``jax.export``): the jitted apply
function is serialized together with the checkpointed parameters, producing a
self-contained directory loadable without the model's Python code.
"""
from __future__ import annotations

import json
import os
from typing import Any, Callable, Optional, Sequence

import jax
import jax.export
import numpy as np

from autodist_tpu.checkpoint.saver import Saver, save_params
from autodist_tpu.utils import logging


def _abstract(x):
    """Shape/dtype without materializing to host (sharded arrays on a
    multi-host mesh are not np.asarray-able)."""
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is None or dtype is None:
        arr = np.asarray(x)
        shape, dtype = arr.shape, arr.dtype
    return jax.ShapeDtypeStruct(tuple(shape), dtype)


class SavedModelBuilder:
    """Export ``apply_fn(params, *inputs)`` + params for serving.

    ``platforms`` controls the lowering targets baked into the artifact;
    the default covers CPU serving of TPU-trained models."""

    def __init__(self, export_dir: str,
                 platforms: Sequence[str] = ("cpu", "tpu")):
        self._dir = export_dir
        self._platforms = tuple(platforms)
        os.makedirs(export_dir, exist_ok=True)

    def add_graph_and_variables(self, apply_fn: Callable, params: Any,
                                example_inputs: Sequence[Any]) -> None:
        """Serialize the function (traced on abstract inputs) and the
        parameter values."""
        abstract_params = jax.tree_util.tree_map(_abstract, params)
        abstract_inputs = tuple(_abstract(x) for x in example_inputs)
        exported = jax.export.export(
            jax.jit(apply_fn), platforms=self._platforms)(
                abstract_params, *abstract_inputs)
        with open(os.path.join(self._dir, "model.stablehlo"), "wb") as f:
            f.write(exported.serialize())
        save_params(os.path.join(self._dir, "variables"), params)
        with open(os.path.join(self._dir, "saved_model_meta.json"), "w",
                  encoding="utf-8") as f:
            json.dump({
                "num_inputs": len(example_inputs),
                "input_shapes": [list(np.shape(x)) for x in example_inputs],
            }, f)

    def save(self) -> str:
        logging.info("saved model exported to %s", self._dir)
        return self._dir


def load_saved_model(export_dir: str):
    """Load an exported model: returns ``fn(*inputs)`` with params bound."""
    with open(os.path.join(export_dir, "model.stablehlo"), "rb") as f:
        exported = jax.export.deserialize(f.read())
    params = Saver.restore_params(os.path.join(export_dir, "variables"))

    def fn(*inputs):
        return exported.call(params, *inputs)

    return fn
